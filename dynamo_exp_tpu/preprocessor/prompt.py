"""Chat-template rendering (Jinja) for prompt formatting.

Capability parity with ``/root/reference/lib/llm/src/preprocessor/prompt/``
(minijinja with HF pycompat): render ``tokenizer_config.json`` chat
templates, including tool-use arguments, with the helpers HF templates
expect (``raise_exception``, ``tojson``, ``strftime_now``).
"""

from __future__ import annotations

import datetime
from typing import Any

import jinja2
from jinja2.sandbox import ImmutableSandboxedEnvironment

from ..model_card import ModelDeploymentCard


class PromptFormatError(ValueError):
    pass


def _raise_exception(message: str) -> None:
    raise PromptFormatError(message)


def _strftime_now(fmt: str) -> str:
    return datetime.datetime.now().strftime(fmt)


class PromptFormatter:
    """Renders OpenAI-style message lists into a single prompt string."""

    def __init__(self, mdc: ModelDeploymentCard):
        self.mdc = mdc
        self._env = ImmutableSandboxedEnvironment(
            trim_blocks=True,
            lstrip_blocks=True,
            keep_trailing_newline=True,
            undefined=jinja2.ChainableUndefined,
        )
        self._env.globals["raise_exception"] = _raise_exception
        self._env.globals["strftime_now"] = _strftime_now
        self._env.filters["tojson"] = lambda v, **kw: __import__("json").dumps(v, **kw)
        self._template = (
            self._env.from_string(mdc.chat_template) if mdc.chat_template else None
        )

    def render(
        self,
        messages: list[dict[str, Any]],
        tools: list[dict[str, Any]] | None = None,
        add_generation_prompt: bool = True,
    ) -> str:
        if self._template is None:
            return self._fallback(messages)
        try:
            return self._template.render(
                messages=messages,
                tools=tools,
                add_generation_prompt=add_generation_prompt,
                bos_token=self.mdc.bos_token or "",
                eos_token=self.mdc.eos_token or "",
            )
        except PromptFormatError:
            raise
        except jinja2.TemplateError as e:
            raise PromptFormatError(f"chat template failed: {e}") from e

    def _fallback(self, messages: list[dict[str, Any]]) -> str:
        """No template in the card: a neutral role-tagged concatenation."""
        parts = []
        for m in messages:
            content = m.get("content") or ""
            if isinstance(content, list):
                content = "".join(
                    p.get("text", "") for p in content if isinstance(p, dict)
                )
            parts.append(f"{m.get('role', 'user')}: {content}")
        parts.append("assistant:")
        return "\n".join(parts)
