"""Seeded sim-in-the-loop knob search (docs/tuning.md).

Coordinate descent with a successive-halving rung over the declarative
knob space (:mod:`.space`): candidates are first scored on one search
seed (rung 0) and only challengers that beat the incumbent's rung-0
score graduate to the full multi-seed evaluation (rung 1). Every
evaluation is one :class:`~dynamo_exp_tpu.sim.cluster.ClusterSim` run
replaying the workload target — a PR 16 fingerprint through
:func:`~dynamo_exp_tpu.telemetry.fingerprint.replay_workload`, a trace
file, or a named synthetic workload.

Determinism contract (dynlint-zoned): no wall clocks, every random
draw comes from ``random.Random(seed)``, and the JSONL trial journal
is byte-identical across same-seed runs — which is what makes a run
resumable: a truncated journal replays as an evaluation cache and the
search rewrites the identical uninterrupted journal.

The composite objective scores goodput per chip-second, discounted by
p99 TTFT/ITL SLO compliance — the three axes the ISSUE names — so a
config that buys throughput by blowing latency targets (or by holding
an overscaled fleet) loses to one that serves the same tokens inside
the SLO envelope for fewer chip-seconds.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field

from ..planner.planner import PlannerConfig
from ..planner.policy import SloTargets
from ..sim.cluster import ClusterSim, SimConfig
from ..sim.fit import ServiceTimeModel
from ..telemetry.fingerprint import (
    WorkloadFingerprint,
    fingerprint_from_trace,
    replay_workload,
)
from . import space

JOURNAL_VERSION = 1


# ------------------------------------------------------------------ target
@dataclass(frozen=True)
class TuneTarget:
    """The workload the search optimizes for. ``fingerprint`` targets
    replay through the PR 16 sim bridge; synthetic targets generate
    from the named ``sim/workload.py`` scenario."""

    kind: str  # "fingerprint" | "synthetic"
    fingerprint: WorkloadFingerprint | None = None
    name: str = ""  # synthetic scenario name
    requests: int = 64
    rate_rps: float | None = None
    duration_s: float = 60.0

    @property
    def digest(self) -> str:
        if self.fingerprint is not None:
            return self.fingerprint.digest()
        return f"synthetic:{self.name}"

    def workload(self, seed: int) -> list:
        if self.fingerprint is not None:
            return replay_workload(
                self.fingerprint,
                seed=seed,
                n=self.requests,
                rate_rps=self.rate_rps,
            )
        from ..sim import workload as wl

        if self.name == "burst":
            return wl.burst_workload(seed, n=self.requests)
        if self.name == "ramp":
            return wl.ramp_workload(
                seed,
                duration_s=self.duration_s,
                rps_start=self.rate_rps or 1.0,
                rps_end=(self.rate_rps or 1.0) * 4,
            )
        if self.name == "diurnal":
            return wl.diurnal_workload(
                seed,
                duration_s=self.duration_s,
                rps_base=self.rate_rps or 1.0,
                rps_peak=(self.rate_rps or 1.0) * 4,
            )
        if self.name == "users":
            return list(
                wl.synthetic_users(
                    seed, users=self.requests, duration_s=self.duration_s
                )
            )
        raise ValueError(f"unknown synthetic workload {self.name!r}")


def target_from_fingerprint(
    fp: WorkloadFingerprint,
    requests: int | None = None,
    rate_rps: float | None = None,
) -> TuneTarget:
    return TuneTarget(
        kind="fingerprint",
        fingerprint=fp,
        requests=requests or max(fp.n, 16),
        rate_rps=rate_rps,
    )


def target_from_trace(
    path: str, requests: int | None = None, rate_rps: float | None = None
) -> TuneTarget:
    """Trace files target through their fingerprint (same bridge, so a
    span capture and its fingerprint file tune identically)."""
    return target_from_fingerprint(
        fingerprint_from_trace(path), requests=requests, rate_rps=rate_rps
    )


# --------------------------------------------------------------- objective
def composite_objective(report) -> dict:
    """Score one sim run. ``goodput_per_chip_s`` is SLO-goodput tokens
    per chip-second (the spend-normalized throughput axis);
    ``score`` discounts it by the TTFT and ITL compliance fractions,
    so capacity bought by blowing p99 targets doesn't count."""
    completed = max(report.completed, 1)
    ttft_ok = 1.0 - min(report.slo_violations_ttft / completed, 1.0)
    itl_ok = 1.0 - min(report.slo_violations_itl / completed, 1.0)
    chip_s = max(report.chip_seconds, 1e-6)
    goodput_tokens = report.goodput_tok_s * report.duration_s
    goodput_per_chip_s = goodput_tokens / chip_s
    return {
        "score": round(goodput_per_chip_s * ttft_ok * itl_ok, 6),
        "goodput_tok_s": report.goodput_tok_s,
        "goodput_per_chip_s": round(goodput_per_chip_s, 4),
        "ttft_compliance": round(ttft_ok, 4),
        "itl_compliance": round(itl_ok, 4),
        "ttft_p99_s": report.ttft_p99_s,
        "itl_p99_s": report.itl_p99_s,
        "chip_seconds": report.chip_seconds,
        "completed": report.completed,
        "shed": report.shed,
        "preemptions": report.preemptions,
    }


# ------------------------------------------------------------------ search
@dataclass
class SearchSettings:
    """Everything one search run depends on, journaled for audit."""

    seed: int = 0
    budget: int = 64  # max sim evaluations (rung-0 + rung-1 both count)
    eval_seeds: int = 2  # seeds per full (rung-1) evaluation
    planner: bool = False  # run the SLO planner; include its knobs
    # Deployment envelope: SimConfig keyword overrides the search does
    # NOT tune (fleet size, service model riding separately).
    base_sim: dict = field(default_factory=dict)
    slo: SloTargets | None = None
    service: ServiceTimeModel | None = None

    def header(self, target: TuneTarget) -> dict:
        return {
            "kind": "header",
            "v": JOURNAL_VERSION,
            "space": space.space_digest(),
            "seed": self.seed,
            "budget": self.budget,
            "eval_seeds": self.eval_seeds,
            "planner": self.planner,
            "base_sim": {k: self.base_sim[k] for k in sorted(self.base_sim)},
            "target": target.digest,
            "requests": target.requests,
        }


@dataclass
class TuneResult:
    best_overrides: dict
    best_score: float
    default_score: float
    trials: int
    journal: list  # every journal line, header included
    target_digest: str
    seed: int

    @property
    def improvement(self) -> float:
        if self.default_score <= 0:
            return 0.0
        return round(self.best_score / self.default_score - 1.0, 4)


def evaluate(
    overrides: dict,
    target: TuneTarget,
    settings: SearchSettings,
    seed: int,
    workload: list | None = None,
) -> dict:
    """One sim run of one candidate on one seed -> objective dict.

    ``workload`` pins an explicit request list (the validation stage
    feeds both sim and live the same one); otherwise the target
    generates it from the seed."""
    split = space.split_overrides(overrides)
    kwargs = dict(settings.base_sim)
    kwargs.update(space.sim_kwargs_from_overrides(overrides))
    slo = settings.slo or SloTargets()
    if settings.planner:
        if split["slo"]:
            from dataclasses import replace

            slo = replace(slo, **split["slo"])
        kwargs.setdefault("planner", "slo")
        kwargs.setdefault("admission_per_instance", True)
        kwargs["planner_cfg"] = PlannerConfig(**split["planner"])
    cfg = SimConfig(
        seed=seed,
        record_events=False,
        service=settings.service or ServiceTimeModel.default(),
        slo=slo,
        **kwargs,
    )
    if workload is None:
        workload = target.workload(seed)
    report = ClusterSim(cfg, workload).run()
    return composite_objective(report)


def _eval_seed(base_seed: int, i: int) -> int:
    """The search's evaluation seeds: a fixed affine family so held-out
    tests can pick seeds provably outside it."""
    return base_seed * 1000 + i


def _canon(overrides: dict) -> str:
    return json.dumps(overrides, sort_keys=True, separators=(",", ":"))


def load_journal(path: str) -> list[dict]:
    """Parse a (possibly truncated) journal; a half-written trailing
    line is dropped, not an error — that is exactly the resume case."""
    out = []
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    out.append(json.loads(raw))
                except ValueError:
                    break  # torn tail write; everything before it counts
    except FileNotFoundError:
        pass
    return out


def top_candidates(result: TuneResult, k: int) -> list[dict]:
    """The k best distinct configs the search fully evaluated (rung 1),
    best first — the validation stage's input. The default config is
    itself a rung-1 trial, so it competes for a slot like any other."""
    seen: set[str] = set()
    out: list[dict] = []
    trials = [
        ln
        for ln in result.journal
        if ln.get("kind") == "trial" and ln.get("rung") == 1
    ]
    for ln in sorted(trials, key=lambda t: -t["score"]):
        key = _canon(ln["overrides"])
        if key in seen:
            continue
        seen.add(key)
        out.append(dict(ln["overrides"]))
        if len(out) >= k:
            break
    return out


def run_search(
    target: TuneTarget,
    settings: SearchSettings,
    journal_path: str | None = None,
    resume: bool = False,
) -> TuneResult:
    """Coordinate descent over the sim-applicable knob grids.

    Pass structure: knob order is drawn once per pass from the seeded
    rng; each off-default grid value is scored at rung 0 (one seed) and
    promoted to the full rung only if it beats the incumbent's rung-0
    score. Passes repeat until a full pass yields no improvement or the
    trial budget is spent.

    ``resume`` replays an existing journal as an evaluation cache: the
    deterministic search path re-derives every decision, cache hits
    skip the sim run, and the rewritten journal is byte-identical to an
    uninterrupted run's.
    """
    knobs = space.sim_knobs(planner=settings.planner)
    rng = random.Random(settings.seed)
    header = settings.header(target)

    cache: dict[tuple[str, int], dict] = {}
    if resume and journal_path:
        prior = load_journal(journal_path)
        if prior and prior[0].get("kind") == "header":
            stale = {
                k: (prior[0].get(k), header[k])
                for k in ("space", "seed", "budget", "target")
                if prior[0].get(k) != header[k]
            }
            if stale:
                raise ValueError(
                    f"journal {journal_path} was written by a different "
                    f"run; mismatched fields: {stale}"
                )
            for line in prior[1:]:
                if line.get("kind") == "trial":
                    for s, comp in zip(line["seeds"], line["evals"]):
                        cache[(_canon(line["overrides"]), s)] = comp

    journal: list[dict] = [header]
    out = open(journal_path, "w") if journal_path else None

    def emit(line: dict) -> None:
        journal.append(line)
        if out is not None:
            out.write(json.dumps(line, sort_keys=True) + "\n")
            out.flush()

    if out is not None:
        out.write(json.dumps(header, sort_keys=True) + "\n")
        out.flush()

    trials = 0

    def run_eval(overrides: dict, seeds: list[int]) -> tuple[float, list]:
        evals = []
        for s in seeds:
            key = (_canon(overrides), s)
            if key not in cache:
                cache[key] = evaluate(overrides, target, settings, s)
            evals.append(cache[key])
        mean = round(sum(e["score"] for e in evals) / len(evals), 6)
        return mean, evals

    full_seeds = [
        _eval_seed(settings.seed, i) for i in range(settings.eval_seeds)
    ]
    rung0_seed = [full_seeds[0]]

    try:
        current: dict = {}
        best_score, evals = run_eval(current, full_seeds)
        best_r0 = evals[0]["score"]
        trials += 1
        emit({
            "kind": "trial", "i": trials, "overrides": current,
            "rung": 1, "seeds": full_seeds, "evals": evals,
            "score": best_score, "best": True,
        })
        default_score = best_score

        improved_any = True
        while improved_any and trials < settings.budget:
            improved_any = False
            order = list(knobs)
            rng.shuffle(order)
            for knob in order:
                if trials >= settings.budget:
                    break
                incumbent = current.get(knob.name, space.default_value(knob))
                for value in knob.grid:
                    if value == incumbent or trials >= settings.budget:
                        continue
                    cand = {
                        k: v for k, v in current.items() if k != knob.name
                    }
                    if value != space.default_value(knob):
                        cand[knob.name] = value
                    s0, evals0 = run_eval(cand, rung0_seed)
                    trials += 1
                    promoted = s0 > best_r0
                    emit({
                        "kind": "trial", "i": trials, "overrides": cand,
                        "rung": 0, "seeds": rung0_seed, "evals": evals0,
                        "score": s0, "best": False,
                        "promoted": promoted,
                    })
                    if not promoted or trials >= settings.budget:
                        continue
                    s_full, evals_full = run_eval(cand, full_seeds)
                    trials += 1
                    adopt = s_full > best_score
                    emit({
                        "kind": "trial", "i": trials, "overrides": cand,
                        "rung": 1, "seeds": full_seeds,
                        "evals": evals_full, "score": s_full,
                        "best": adopt,
                    })
                    if adopt:
                        current = cand
                        best_score = s_full
                        best_r0 = evals_full[0]["score"]
                        incumbent = current.get(
                            knob.name, space.default_value(knob)
                        )
                        improved_any = True

        emit({
            "kind": "result",
            "best_overrides": {k: current[k] for k in sorted(current)},
            "best_score": best_score,
            "default_score": default_score,
            "trials": trials,
            "target": target.digest,
        })
    finally:
        if out is not None:
            out.close()

    return TuneResult(
        best_overrides={k: current[k] for k in sorted(current)},
        best_score=best_score,
        default_score=default_score,
        trials=trials,
        journal=journal,
        target_digest=target.digest,
        seed=settings.seed,
    )
