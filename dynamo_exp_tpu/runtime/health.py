"""Instance health tracking for the request plane.

Two pieces compose the fault-tolerance story (see
``docs/fault_tolerance.md``):

- :class:`CircuitBreaker` — one consecutive-failure breaker with the
  classic closed → open → half-open cycle. Used standalone for single
  remote dependencies (the disagg prefill fleet behind its work queue)
  and per-instance inside the tracker.
- :class:`HealthTracker` — per-instance breakers plus discovery-fed
  liveness (snapshot timestamps, draining metadata), owned by every
  :class:`~dynamo_exp_tpu.runtime.client.Client`. Request outcomes feed
  it from :class:`~dynamo_exp_tpu.runtime.push_router.PushRouter`;
  discovery snapshots feed it from ``Client._watch``.

State transitions land on the ``dynamo_circuit_breaker_transitions_total``
counter so operators can see flapping instances on ``/metrics``.
"""

from __future__ import annotations

import enum
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable

from ..telemetry import get_telemetry
from .transports.base import InstanceInfo

logger = logging.getLogger(__name__)

# Metadata key a draining worker publishes to discovery; routers treat a
# truthy value as "no new work".
DRAINING_KEY = "draining"

# Metadata key a spot-reclaimed worker publishes (docs/fault_tolerance.md
# "Spot reclamation & live migration"): same routing consequence as
# draining — no new work within one watch event — but the window is a
# hard platform deadline, not a goodbye the worker controls, so the
# reclaim plane additionally triages in-flight sequences under it.
RECLAIMING_KEY = "reclaiming"


def is_draining(info: InstanceInfo) -> bool:
    return bool(info.metadata.get(DRAINING_KEY))


def is_reclaiming(info: InstanceInfo) -> bool:
    return bool(info.metadata.get(RECLAIMING_KEY))


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with a half-open probe.

    ``allow()`` answers "may I send work now?": always in CLOSED; never
    inside the OPEN cooldown; exactly one caller (the probe) per
    half-open window after the cooldown. ``record_success`` closes,
    ``record_failure`` (re)opens once ``failure_threshold`` consecutive
    failures accumulate — or immediately when the half-open probe fails.

    ``clock`` is injectable so tests can step time deterministically.
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
    ):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.name = name
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def _transition(self, state: BreakerState) -> None:
        if state is self.state:
            return
        self.state = state
        get_telemetry().breaker_transitions.labels(state.value).inc()
        logger.info("circuit breaker %s -> %s", self.name or "?", state.value)

    def allow(self) -> bool:
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.OPEN:
            if self.clock() - self._opened_at < self.cooldown_s:
                return False
            self._transition(BreakerState.HALF_OPEN)
            self._probe_inflight = False
        # HALF_OPEN: admit a single probe until its outcome is recorded.
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        return True

    def would_allow(self) -> bool:
        """``allow()`` without claiming the half-open probe slot."""
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.OPEN:
            return self.clock() - self._opened_at >= self.cooldown_s
        return not self._probe_inflight

    def release(self) -> None:
        """Release a claimed probe slot **without recording an outcome**.

        Some dispatch paths end with neither success nor failure evidence
        about the instance — the request's own deadline expired, the
        caller was cancelled mid-await. Without this, the slot claimed by
        ``allow()`` leaks: the breaker sticks in HALF_OPEN with
        ``_probe_inflight`` set forever and the instance becomes
        permanently unroutable. Callers must pair every ``allow()`` with
        exactly one of record_success / record_failure / release."""
        self._probe_inflight = False

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._probe_inflight = False
        self._transition(BreakerState.CLOSED)

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        self._probe_inflight = False
        if self.state is BreakerState.HALF_OPEN or (
            self.state is BreakerState.CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self._opened_at = self.clock()
            self._transition(BreakerState.OPEN)
        elif self.state is BreakerState.OPEN:
            # Failures while already open (racing in-flight requests)
            # restart the cooldown so a dead instance is not probed
            # while still provably failing.
            self._opened_at = self.clock()

    @property
    def is_open(self) -> bool:
        return self.state is BreakerState.OPEN


@dataclass
class _InstanceHealth:
    breaker: CircuitBreaker
    last_seen: float = 0.0
    failures_total: int = field(default=0)


class HealthTracker:
    """Per-instance health over request outcomes + discovery liveness.

    ``stale_after_s`` (optional) excludes instances whose discovery
    snapshot is older than the window — heartbeat staleness for fabrics
    whose watch stream has gone quiet. Disabled by default because the
    in-proc discovery only pushes on membership *change*.
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown_s: float = 5.0,
        stale_after_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.stale_after_s = stale_after_s
        self.clock = clock
        self._instances: dict[int, _InstanceHealth] = {}

    def _entry(self, instance_id: int) -> _InstanceHealth:
        entry = self._instances.get(instance_id)
        if entry is None:
            entry = self._instances[instance_id] = _InstanceHealth(
                breaker=CircuitBreaker(
                    self.failure_threshold,
                    self.cooldown_s,
                    clock=self.clock,
                    name=f"instance-{instance_id}",
                ),
                last_seen=self.clock(),
            )
        return entry

    # ---------------------------------------------------------- outcomes
    def record_success(self, instance_id: int) -> None:
        self._entry(instance_id).breaker.record_success()

    def record_failure(self, instance_id: int) -> None:
        entry = self._entry(instance_id)
        entry.failures_total += 1
        entry.breaker.record_failure()

    def release(self, instance_id: int) -> None:
        """Outcome-free release of an :meth:`acquire` claim (deadline
        expiry, cancellation — paths that say nothing about the
        instance's health). See :meth:`CircuitBreaker.release`."""
        self._entry(instance_id).breaker.release()

    def breaker(self, instance_id: int) -> CircuitBreaker:
        return self._entry(instance_id).breaker

    # --------------------------------------------------------- discovery
    def observe_instances(self, infos: Iterable[InstanceInfo]) -> None:
        """Feed a discovery snapshot: stamps liveness and drops health
        state for instances that left (their ids are lease-derived and
        never reused, so the state is dead weight)."""
        now = self.clock()
        seen = set()
        for info in infos:
            seen.add(info.instance_id)
            self._entry(info.instance_id).last_seen = now
        for iid in list(self._instances):
            if iid not in seen:
                del self._instances[iid]

    # ----------------------------------------------------------- queries
    def is_available(self, info: InstanceInfo) -> bool:
        """Routable right now: not draining, not reclaiming, not
        breaker-blocked, not stale. Does NOT claim the half-open probe
        slot — selection does that via :meth:`acquire`."""
        if is_draining(info) or is_reclaiming(info):
            return False
        entry = self._instances.get(info.instance_id)
        if entry is None:
            return True
        if (
            self.stale_after_s is not None
            and entry.last_seen
            and self.clock() - entry.last_seen > self.stale_after_s
        ):
            return False
        return entry.breaker.would_allow()

    def acquire(self, instance_id: int) -> bool:
        """Claim the right to dispatch to the instance (consumes the
        half-open probe slot when the breaker is recovering)."""
        return self._entry(instance_id).breaker.allow()

    def filter_available(
        self, infos: Iterable[InstanceInfo]
    ) -> list[InstanceInfo]:
        return [i for i in infos if self.is_available(i)]

    def unavailable_ids(self, infos: Iterable[InstanceInfo]) -> set[int]:
        return {i.instance_id for i in infos if not self.is_available(i)}
