"""Benchmark: engine decode throughput on the real TPU chip.

Default mode prints ONE JSON line: {"metric", "value", "unit",
"vs_baseline"} for the driver.

``--sweep`` runs the reference harness shape scaled to one chip —
ISL 3000 / OSL 150 fixed lengths, ignore_eos, concurrency sweep
(``/root/reference/examples/llm/benchmarks/perf.sh:22-44`` uses 1→256
on 8×H100; one v5e chip sweeps 1→32) — and prints one JSON line per
concurrency point.

``vs_baseline`` is measured tok/s divided by the single-chip HBM
roofline for this model (weights are re-read every decode step, so
steps/s <= HBM_BW / weight_bytes; tokens/s <= steps/s * batch). This is
an honest hardware-efficiency fraction rather than a cross-hardware
comparison the reference never published absolute numbers for
(SURVEY.md §6).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import numpy as np

MODEL = "llama-1b"
ISL = 128
OSL = 64
CONCURRENCY = 32
# The CPU fallback's default point: a 1B model at the TPU shape is
# hours on a CI box's cores, which reads as a dead bench run — and even
# the trimmed shape is minutes of f32 weight init + compile there, so
# the fallback also drops to the ``tiny`` preset. The metric name
# carries the model and shape and every line carries the platform, so
# the trajectory stays unambiguous. Explicit --model/--isl/--osl/
# --concurrency always win.
CPU_MODEL = "tiny"
CPU_ISL = 64
CPU_OSL = 32
CPU_CONCURRENCY = 4
HBM_GBPS = 819.0  # TPU v5e

SWEEP_ISL = 3000
SWEEP_OSL = 150
SWEEP_CONCURRENCY = (1, 4, 16, 32)
# CPU-fallback sweep shapes: the reference sweep point (ISL 3000 at
# concurrency 32) is the "hours on a CI box" case above even with the
# tiny preset, so every sweep mode trims the same way the default
# point does — the emitted shape labels + platform tag keep fallback
# lines distinguishable from chip lines.
CPU_SWEEP_ISL = 256
CPU_SWEEP_OSL = 32
CPU_SWEEP_CONCURRENCY = (1, 2, 4)
CPU_SWEEP_KW = dict(slots=4, isl=128, osl=32)  # occupancy/overload sweeps
# Offload-pressure axis CPU trim (occupancy sweep only — the shared
# CPU_SWEEP_KW also feeds run_overload_sweep, which has no such axis).
CPU_PRESSURE_MULTIPLES = (1, 2, 4)
CPU_OVERLOAD_BURSTS = (4, 8, 16)
CPU_PREFIX_KW = dict(isl=256, osl=8, concurrency=4)
# Prefix-sharing sweep CPU fallback: same trim treatment — tiny shapes,
# two ratio points, enough to exercise shared-vs-private both arms.
CPU_PREFIX_SWEEP_KW = dict(
    isl=128, osl=8, concurrency=4, ratios=(0.0, 0.75)
)
# Spec-sweep CPU fallback: same trimming policy as every other sweep —
# tiny shapes, one draft length besides the off baseline.
CPU_SPEC_KW = dict(slots=2, isl=96, osl=32, draft_lens=(0, 4))

# Coldstart sweep CPU fallback: small shapes, the same trim policy.
CPU_COLDSTART_KW = dict(isl=64, osl=16, concurrency=2)

# Reclaim sweep CPU trim: the sweep is sim-driven (no chip, no
# compile), so the trim only shortens the simulated window and drops a
# rate point to keep the CI lane seconds-scale.
CPU_RECLAIM_KW = dict(duration_s=120.0, reclaim_rates=(0.0, 2.0, 6.0))

# Restart sweep CPU fallback: same trim policy as coldstart — tiny
# shapes, both arms still exercised end to end.
CPU_RESTART_KW = dict(isl=64, osl=16, concurrency=2)

# Burst policy: warmup rounds (compile + program load) and timed rounds
# (best-of). The CPU fallback trims both to 1 — XLA:CPU timings are
# low-variance and a 1B-model burst is minutes, not seconds, there.
WARMUP_BURSTS = 2
TIMED_BURSTS = 3
# Set by the probe's CPU fallback: run the model in float32 there
# (XLA:CPU software-emulates bfloat16 matmuls — order-of-magnitude
# slower than native f32 on the same cores).
CPU_FALLBACK = False
# AOT warm boot (docs/aot.md): --prewarm prewarns every bench engine
# before measurement. LINE_TAGS rides on every JSON line so sim/fit.py
# can tell warm samples from cold (the manifest hash pins which compile
# lattice produced the numbers).
PREWARM = False
LINE_TAGS = {
    "prewarmed": False,
    "manifest_hash": None,
    # Resolved tunable-knob dict + stable hash (tune/space.py), filled
    # in by _build_engine; None until an engine exists.
    "knobs": None,
    "config_hash": None,
}


def _preset(name: str):
    from dataclasses import replace

    from dynamo_exp_tpu.models import PRESETS

    mcfg = PRESETS[name]
    return replace(mcfg, dtype="float32") if CPU_FALLBACK else mcfg


def _kv_dtype() -> str:
    return "float32" if CPU_FALLBACK else "bfloat16"


def _roofline_tok_s(params, batch: int) -> float:
    import jax

    weight_bytes = sum(
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree.leaves(params)
    )
    return HBM_GBPS * 1e9 / weight_bytes * batch


def _dispatch_stats(engine) -> dict:
    """Per-kind dispatch-timing percentiles (p50/p99 host-gap and
    in-flight) from the engine's dispatch profiler, attached to every
    bench JSON line — so ``sim/fit.py --fit-bench`` can fit service
    times without a span file (it reads ``dispatch.ragged`` — or the
    retired ``dispatch.decode`` of pre-ragged bench files — together
    with the line's ``decode_window``). Kinds that never dispatched in
    the run keep count 0 / null percentiles."""
    disp = engine.metrics().get("dispatch") or {}
    keep = (
        "count",
        "host_gap_p50_s",
        "host_gap_p99_s",
        "in_flight_p50_s",
        "in_flight_p99_s",
    )
    return {
        kind: {f: stats.get(f) for f in keep}
        for kind, stats in disp.items()
    }


def _anatomy_stats(engine) -> dict:
    """Mean per-request latency anatomy (telemetry/anatomy.py component
    seconds over finished requests) attached to every bench JSON line,
    so ``llmctl bench compare`` can attribute a throughput regression
    to the component that moved (queue vs prefill vs decode vs swap
    stall) instead of just flagging the headline number. Zero-valued
    components are dropped to keep bench lines compact."""
    m = engine.metrics()
    n = m.get("anatomy_requests") or 0
    totals = m.get("anatomy_totals") or {}
    if not n:
        return {}
    return {comp: round(sec / n, 6) for comp, sec in totals.items() if sec}


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (docs/aot.md): repeat bench
    runs (and the driver's end-of-round run) skip the 20-40s
    per-variant compiles, so the measured TTFT reflects serving, not
    compilation. ``DYN_COMPILE_CACHE`` overrides the default path."""
    from dynamo_exp_tpu.aot import cache_dir_from_env, enable_persistent_cache

    enable_persistent_cache(
        cache_dir_from_env() or "/tmp/dynamo_tpu_jax_cache"
    )


def _build_engine(cfg, params=None, seed: int = 0):
    """Every bench engine goes through here: tags each subsequent JSON
    line with the engine's compile-manifest hash, whether it was
    warm-booted (``--prewarm``), and the resolved tunable-knob dict
    plus its stable ``config_hash`` (tune/space.py) — so
    ``llmctl bench compare`` pairs lines knobbed identically instead of
    silently comparing differently-tuned runs, and ``sim/fit.py`` can
    split warm from cold samples (docs/aot.md, docs/tuning.md)."""
    from dynamo_exp_tpu.aot import manifest_for_engine
    from dynamo_exp_tpu.engine import TPUEngine
    from dynamo_exp_tpu.tune import space as tune_space

    engine = TPUEngine(cfg, params=params, seed=seed)
    manifest = manifest_for_engine(engine)
    if PREWARM:
        engine.prewarm(manifest)
    knobs = tune_space.resolved_engine_knobs(cfg)
    LINE_TAGS.update(
        prewarmed=bool(PREWARM),
        manifest_hash=manifest.hash(),
        knobs=knobs,
        config_hash=tune_space.config_hash(knobs),
    )
    engine.start()
    return engine


def run_point(isl: int, osl: int, concurrency: int) -> dict:
    """One measured point: build an engine, double-warm, time a burst."""
    from dynamo_exp_tpu.engine import EngineConfig
    from dynamo_exp_tpu.protocols.common import BackendInput

    _enable_compile_cache()

    mcfg = _preset(MODEL)
    cfg = EngineConfig(
        model=mcfg,
        max_decode_slots=concurrency,
        page_size=16,
        num_pages=concurrency * ((isl + osl) // 16 + 2) + 64,
        max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
        eos_token_ids=[],
        kv_dtype=_kv_dtype(),
        # One host sync per 32 decode steps: throughput benches are
        # sync-bound long before they are FLOP-bound on a tunneled chip.
        decode_window=32,
    )
    engine = _build_engine(cfg)

    rs = np.random.RandomState(0)

    # Fresh tokens for every burst: identical shapes hit the same
    # compiled variants, distinct tokens keep the prefix cache honest
    # (re-serving a previous burst's prompts would measure warm-cache
    # prefill instead of steady-state decode).
    def fresh_prompts() -> list[list[int]]:
        return [
            rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
            for _ in range(concurrency)
        ]

    warmups = fresh_prompts()

    async def run_one(prompt):
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = osl
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict())
        n = 0
        ttft = None
        t0 = time.perf_counter()
        async for item in stream:
            if item.get("token_ids") and ttft is None:
                ttft = time.perf_counter() - t0
            n += len(item.get("token_ids", []))
        return n, ttft

    async def burst():
        # Warmup: two full concurrent bursts. The first compiles every
        # variant (prefill row/token buckets, decode window); the second
        # matters because the tunnel's AOT compile path also makes the
        # *second* execution of a fresh executable slow (program load).
        # Steady-state throughput, not compile/load time, is the metric.
        for _ in range(WARMUP_BURSTS):
            await asyncio.gather(*[run_one(p) for p in warmups])
        # Best of three timed bursts: the tunneled chip's latency is
        # high-variance, and peak steady-state is the honest capability
        # number a flaky link can still demonstrate.
        best = None
        for burst_prompts in (fresh_prompts() for _ in range(TIMED_BURSTS)):
            t0 = time.perf_counter()
            results = await asyncio.gather(*[run_one(p) for p in burst_prompts])
            dt = time.perf_counter() - t0
            total = sum(n for n, _ in results)
            ttfts = sorted(t for _, t in results if t is not None)
            point = (total / dt, ttfts[len(ttfts) // 2])
            if best is None or point[0] > best[0]:
                best = point
        return best

    tok_s, p50_ttft = asyncio.run(burst())
    roofline = _roofline_tok_s(engine.params, concurrency)
    dispatch = _dispatch_stats(engine)
    anatomy = _anatomy_stats(engine)
    engine.stop()
    return {
        "metric": f"decode_throughput_{MODEL}_isl{isl}_osl{osl}_c{concurrency}",
        "value": round(tok_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / roofline, 4),
        "p50_ttft_s": round(p50_ttft, 3),
        "decode_window": engine.cfg.decode_window,
        "dispatch": dispatch,
        "anatomy": anatomy,
    }


def run_occupancy_sweep(
    slots: int = 8,
    isl: int = 512,
    osl: int = 128,
    pressure_multiples: tuple = (1, 2, 4, 8),
) -> list[dict]:
    """Decode throughput vs *occupancy* on a fixed-slot engine.

    The compiled decode window is row-compacted (docs/engine_perf.md):
    at 1 active sequence of ``slots`` slots the engine should pick the
    rows=1 variant and pay ~1/slots of the full-batch FLOPs/HBM — this
    sweep captures that curve plus the compiled-variant counts and
    wasted-step counters, so BENCH_r* records regressions where decode
    cost snaps back to the worst case."""
    import asyncio

    from dynamo_exp_tpu.engine import EngineConfig
    from dynamo_exp_tpu.protocols.common import BackendInput

    _enable_compile_cache()
    mcfg = _preset(MODEL)
    cfg = EngineConfig(
        model=mcfg,
        max_decode_slots=slots,
        page_size=16,
        num_pages=slots * ((isl + osl) // 16 + 2) + 64,
        max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
        eos_token_ids=[],
        kv_dtype=_kv_dtype(),
        decode_window=32,
    )
    engine = _build_engine(cfg)
    rs = np.random.RandomState(0)

    async def run_one(prompt):
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = osl
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict())
        n = 0
        async for item in stream:
            n += len(item.get("token_ids", []))
        return n

    def prompts(n):
        return [
            rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
            for _ in range(n)
        ]

    async def point(active: int) -> float:
        # Double warmup per occupancy (compile + program load), then
        # best-of-three timed bursts (same policy as run_point).
        for _ in range(WARMUP_BURSTS):
            await asyncio.gather(*[run_one(p) for p in prompts(active)])
        best = 0.0
        for _ in range(TIMED_BURSTS):
            batch = prompts(active)
            t0 = time.perf_counter()
            results = await asyncio.gather(*[run_one(p) for p in batch])
            dt = time.perf_counter() - t0
            best = max(best, sum(results) / dt)
        return best

    out = []
    occupancies = sorted({1, 2, 4, slots})
    for active in occupancies:
        wasted0 = engine.wasted_steps
        moves0 = engine.kv_page_moves
        tok_s = asyncio.run(point(active))
        m = engine.metrics()
        out.append(
            {
                "metric": f"decode_occupancy_{MODEL}_isl{isl}_osl{osl}"
                f"_a{active}of{slots}",
                "value": round(tok_s, 1),
                "unit": "tok/s",
                "vs_baseline": round(
                    tok_s / _roofline_tok_s(engine.params, active), 4
                ),
                "active": active,
                "slots": slots,
                "compiled_ragged_variants": m["compiled_ragged_variants"],
                "wasted_steps": engine.wasted_steps - wasted0,
                "kv_page_moves": engine.kv_page_moves - moves0,
                "decode_window": engine.cfg.decode_window,
                "dispatch": _dispatch_stats(engine),
                "anatomy": _anatomy_stats(engine),
            }
        )

    # ---------------- mixed prefill+decode axis (ragged late-join) ----------
    # Late prompts injected MID-decode: `active` established decoders
    # chain windows; once they have visibly stepped, `n_late` short
    # prompts arrive. On the ragged engine a latecomer's chunk rides
    # the next compute dispatch together with the decode rows (one
    # mixed program), so its TTFT should sit under one decode-window
    # duration — `late_join_ttft_p50_s` vs the window's in-flight p50
    # is the acceptance comparison, and the variant count shows the
    # mixed shapes landing in the SAME compiled cache.
    isl_late = max(isl // 4, 16)
    n_late = max(slots - max(slots // 2, 1), 1)
    # Established rows must still be decoding when the lates land: give
    # them several windows of runway past the injection point.
    long_osl = max(osl, 6 * cfg.decode_window)

    async def long_one(prompt):
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = long_osl
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict())
        n = 0
        async for item in stream:
            n += len(item.get("token_ids", []))
        return n

    async def late_one(prompt):
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 8
        b.stop_conditions.ignore_eos = True
        t0 = time.perf_counter()
        stream = await engine.generate(b.to_dict())
        ttft = None
        n = 0
        async for item in stream:
            if item.get("token_ids") and ttft is None:
                ttft = time.perf_counter() - t0
            n += len(item.get("token_ids", []))
        return n, ttft

    async def mixed_point(active: int) -> tuple[float, list, float]:
        def late_prompts():
            return [
                rs.randint(10, mcfg.vocab_size - 10, size=isl_late).tolist()
                for _ in range(n_late)
            ]

        # Warmup: compile the mixed (prefill+decode in one dispatch)
        # variants this axis exercises, then time one injection burst.
        for _ in range(WARMUP_BURSTS):
            jobs = [
                asyncio.ensure_future(long_one(p)) for p in prompts(active)
            ]
            await asyncio.sleep(0)
            lates = [asyncio.ensure_future(late_one(p)) for p in late_prompts()]
            await asyncio.gather(*jobs, *lates)
        jobs = [asyncio.ensure_future(long_one(p)) for p in prompts(active)]
        # Wait until the established rows have demonstrably stepped
        # (at least one full decode window) before injecting.
        steps0 = engine.steps
        t0 = time.perf_counter()
        while (
            engine.steps < steps0 + engine.cfg.decode_window
            and time.perf_counter() - t0 < 60.0
        ):
            await asyncio.sleep(0.005)
        lates = [asyncio.ensure_future(late_one(p)) for p in late_prompts()]
        results = await asyncio.gather(*jobs, *lates)
        dt = time.perf_counter() - t0
        total = sum(r[0] if isinstance(r, tuple) else r for r in results)
        ttfts = sorted(t for _, t in results[active:] if t is not None)
        return total / dt, ttfts, dt

    for active in sorted({1, max(slots // 2, 1)}):
        tok_s, ttfts, _dt = asyncio.run(mixed_point(active))
        m = engine.metrics()
        disp = _dispatch_stats(engine)
        # Windows are the slowest ragged dispatches in this phase, so
        # the kind's in-flight p99 approximates one full decode-window
        # duration — the bound the late-join TTFT is judged against
        # (mixed single-step batches drag the p50 far below it).
        window_s = (disp.get("ragged") or {}).get("in_flight_p99_s")
        p50_ttft = ttfts[len(ttfts) // 2] if ttfts else None
        out.append(
            {
                "metric": f"decode_mixed_{MODEL}_isl{isl}_osl{osl}"
                f"_a{active}of{slots}_late{n_late}",
                "value": round(tok_s, 1),
                "unit": "tok/s",
                "vs_baseline": round(
                    tok_s / _roofline_tok_s(engine.params, active + n_late), 4
                ),
                "active": active,
                "slots": slots,
                "late": n_late,
                "late_isl": isl_late,
                "late_join_ttft_p50_s": round(p50_ttft, 4)
                if p50_ttft is not None
                else None,
                "window_in_flight_p99_s": window_s,
                "compiled_ragged_variants": m["compiled_ragged_variants"],
                "decode_window": engine.cfg.decode_window,
                "dispatch": disp,
                "anatomy": _anatomy_stats(engine),
            }
        )
    engine.stop()

    # -------- offload-pressure axis (predictive KV tiering) --------
    # The ROADMAP's named proof surface: hold the pool fixed and scale
    # the AGGREGATE context to multiples of it. One line per multiple,
    # tagged with the tiering counters (prefetch hit rate, proactive
    # offloads, swap-ins) plus the preemptions and p99 ITL the policy
    # is supposed to bound — at 8x pool a healthy line shows proactive
    # offloads absorbing the pressure with preemptions near zero.
    per_seq_pages = (isl + osl) // 16 + 2
    pool = max(2 * per_seq_pages, (slots * per_seq_pages) // 2)
    for mult in pressure_multiples:
        n_req = max(-(-mult * pool * 16 // (isl + osl)), 1)
        pcfg = EngineConfig(
            model=mcfg,
            max_decode_slots=slots,
            page_size=16,
            num_pages=pool,
            max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
            eos_token_ids=[],
            kv_dtype=_kv_dtype(),
            decode_window=32,
            host_cache_pages=pool * 8,
            preempt_stall_grace_s=0.5,
        )
        peng = _build_engine(pcfg)

        async def pressure_one(prompt, eng=peng):
            b = BackendInput(token_ids=prompt)
            b.stop_conditions.max_tokens = osl
            b.stop_conditions.ignore_eos = True
            stream = await eng.generate(b.to_dict())
            n = 0
            gaps: list[float] = []
            last = None
            async for item in stream:
                got = len(item.get("token_ids", []))
                if got:
                    now_t = time.perf_counter()
                    if last is not None:
                        gaps.append((now_t - last) / got)
                    last = now_t
                    n += got
            return n, gaps

        async def pressure_point(n=n_req):
            batch = [
                rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
                for _ in range(n)
            ]
            t0 = time.perf_counter()
            results = await asyncio.gather(*[pressure_one(p) for p in batch])
            dt = time.perf_counter() - t0
            total = sum(n for n, _ in results)
            itls = sorted(g for _, gaps in results for g in gaps)
            p99 = itls[min(int(len(itls) * 0.99), len(itls) - 1)] if itls else None
            return total / dt, p99

        tok_s, p99_itl = asyncio.run(pressure_point())
        m = peng.metrics()
        restored = m["kv_prefetch_pages"]
        hit_rate = (
            round(m["kv_prefetch_hits"] / restored, 4) if restored else None
        )
        out.append(
            {
                "metric": f"kv_tiering_{MODEL}_isl{isl}_osl{osl}_x{mult}",
                "value": round(tok_s, 1),
                "unit": "tok/s",
                "aggregate_x_pool": mult,
                "requests": n_req,
                "pool_pages": pool,
                "host_pages": pool * 8,
                "prefetch_restored_pages": restored,
                "prefetch_hit_rate": hit_rate,
                "proactive_offloads": m["kv_proactive_offloads"],
                "swap_ins": m["kv_swap_ins"],
                "preemptions": m["preemptions"],
                "p99_itl_s": round(p99_itl, 4) if p99_itl is not None else None,
                "decode_window": peng.cfg.decode_window,
                "dispatch": _dispatch_stats(peng),
                "anatomy": _anatomy_stats(peng),
            }
        )
        peng.stop()
    return out


def run_overload_sweep(
    slots: int = 8,
    isl: int = 512,
    osl: int = 128,
    burst_levels: tuple[int, ...] = (8, 16, 32, 64),
) -> list[dict]:
    """Graceful degradation under bursts: goodput, shed rate, p99 TTFT,
    and KV-pressure preemption count per burst level.

    The engine gets a pool sized to roughly *half* its slots' worst-case
    KV need, behind an AdmissionController capped at 2x slots — so
    rising burst levels walk the whole overload ladder: full batches,
    engine-side queuing, KV-pressure preemption, priority shedding
    (429), hard-cap refusals (503). The JSON lines record the curve the
    overload-protection layer is supposed to flatten: goodput should
    plateau near capacity instead of collapsing, and shed rate should
    absorb the excess."""
    import asyncio

    from dynamo_exp_tpu.engine import EngineConfig
    from dynamo_exp_tpu.http.admission import (
        AdmissionController,
        RequestShedError,
        parse_priority,
    )
    from dynamo_exp_tpu.protocols.common import BackendInput

    _enable_compile_cache()
    mcfg = _preset(MODEL)
    pages_per_seq = (isl + osl) // 16 + 2
    cfg = EngineConfig(
        model=mcfg,
        max_decode_slots=slots,
        page_size=16,
        num_pages=(slots * pages_per_seq) // 2 + 16,  # deliberate pressure
        max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
        eos_token_ids=[],
        kv_dtype=_kv_dtype(),
        decode_window=32,
        preempt_stall_grace_s=0.2,
    )
    engine = _build_engine(cfg)
    rs = np.random.RandomState(0)
    priorities = ("low", "normal", "high")

    async def run_one(prompt, priority, admission):
        try:
            admission.acquire(parse_priority(priority))
        except RequestShedError as e:
            return {"shed": e.status}
        try:
            b = BackendInput(
                token_ids=prompt, priority=parse_priority(priority)
            )
            b.stop_conditions.max_tokens = osl
            b.stop_conditions.ignore_eos = True
            stream = await engine.generate(b.to_dict())
            n = 0
            ttft = None
            t0 = time.perf_counter()
            async for item in stream:
                if item.get("token_ids") and ttft is None:
                    ttft = time.perf_counter() - t0
                n += len(item.get("token_ids", []))
            return {"tokens": n, "ttft": ttft}
        finally:
            admission.release()

    async def burst(n: int) -> dict:
        admission = AdmissionController(
            max_inflight=slots * 2, shed_watermark=(slots * 3) // 2
        )
        prompts = [
            rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
            for _ in range(n)
        ]
        preempted0 = engine.preempted
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *[
                run_one(p, priorities[i % len(priorities)], admission)
                for i, p in enumerate(prompts)
            ]
        )
        dt = time.perf_counter() - t0
        done = [r for r in results if "tokens" in r]
        shed = [r for r in results if "shed" in r]
        ttfts = sorted(r["ttft"] for r in done if r["ttft"] is not None)
        return {
            "metric": f"overload_burst_{MODEL}_isl{isl}_osl{osl}_b{n}",
            "value": round(sum(r["tokens"] for r in done) / dt, 1),
            "unit": "goodput tok/s",
            "vs_baseline": round(
                sum(r["tokens"] for r in done)
                / dt
                / _roofline_tok_s(engine.params, slots),
                4,
            ),
            "burst": n,
            "admitted": len(done),
            "shed": len(shed),
            "shed_rate": round(len(shed) / n, 3),
            "shed_429": sum(1 for r in shed if r["shed"] == 429),
            "shed_503": sum(1 for r in shed if r["shed"] == 503),
            "p99_ttft_s": round(ttfts[int(0.99 * (len(ttfts) - 1))], 3)
            if ttfts
            else None,
            "preemptions": engine.preempted - preempted0,
            "decode_window": engine.cfg.decode_window,
            "dispatch": _dispatch_stats(engine),
            "anatomy": _anatomy_stats(engine),
        }

    out = []
    # Warmup at the smallest level: compile prefill/decode variants so
    # the measured TTFTs reflect serving, not compilation.
    asyncio.run(burst(min(burst_levels)))
    for n in burst_levels:
        out.append(asyncio.run(burst(n)))
    engine.stop()
    return out


def run_spec_sweep(
    slots: int = 4,
    isl: int = 512,
    osl: int = 128,
    draft_lens: tuple[int, ...] = (0, 2, 4, 8),
) -> list[dict]:
    """Speculative decoding: tok/s + acceptance across draft lengths
    and workload repetitiveness (docs/speculative.md).

    Two workloads bound the drafter's operating range: ``repeat``
    prompts tile one random block (prefix-repetitive — the prompt-
    lookup n-gram match should hit, acceptance and tokens-per-dispatch
    should rise above 1), ``random`` prompts have no repeated structure
    (lookup mostly misses and the adaptive controller's miss backoff
    should keep the overhead near zero). ``draft_lens`` sweeps the
    pinned per-row draft length; 0 is the speculation-off baseline.
    Every JSON line carries the draft config and the measured
    acceptance, so the sim's service-time fit can learn
    tokens-per-dispatch from these lines."""
    import asyncio

    from dynamo_exp_tpu.engine import EngineConfig
    from dynamo_exp_tpu.protocols.common import BackendInput

    _enable_compile_cache()
    mcfg = _preset(MODEL)
    rs = np.random.RandomState(0)

    def engine_cfg(n_slots: int, spec_mode: str, draft: int) -> "EngineConfig":
        return EngineConfig(
            model=mcfg,
            max_decode_slots=n_slots,
            page_size=16,
            num_pages=n_slots * ((isl + osl) // 16 + 2) + 64,
            max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
            eos_token_ids=[],
            kv_dtype=_kv_dtype(),
            decode_window=8,
            spec_mode=spec_mode,
            spec_draft_len=max(draft, 1),
            spec_max_draft=max(draft, 1),
            # Pin the draft length: this sweep measures the length axis
            # itself, not the controller's trajectory.
            spec_adaptive=False,
        )

    def probe_block(n: int) -> list[int]:
        """The model's own greedy tail over a random prompt: a genuinely
        prefix-repetitive workload must repeat content the model
        actually continues (an arbitrary random block tiled into a
        prompt is repetitive to the *drafter* but not to the target's
        greedy trajectory, so acceptance would measure luck)."""
        eng = _build_engine(engine_cfg(1, "off", 0))

        async def gen(prompt):
            b = BackendInput(token_ids=prompt)
            b.stop_conditions.max_tokens = osl
            b.stop_conditions.ignore_eos = True
            stream = await eng.generate(b.to_dict())
            toks = []
            async for item in stream:
                toks.extend(item.get("token_ids", []))
            return toks

        tail = asyncio.run(
            gen(rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist())
        )[-n:]
        eng.stop()
        return [int(t) for t in tail]

    def build_prompts(workload: str) -> list[list[int]]:
        if workload == "repeat":
            block = probe_block(16)
            return [block * (isl // 16) for _ in range(slots)]
        return [
            rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
            for _ in range(slots)
        ]

    out = []
    for workload in ("repeat", "random"):
        # One fixed prompt set per workload: the sweep's axis is the
        # draft length, so every draft point (incl. the d0 baseline)
        # must serve the SAME prompts or the deltas mix in prompt
        # variation.
        workload_prompts = build_prompts(workload)
        for draft in draft_lens:
            cfg = engine_cfg(slots, "off" if draft == 0 else "ngram", draft)
            engine = _build_engine(cfg)

            async def run_one(prompt):
                b = BackendInput(token_ids=prompt)
                b.stop_conditions.max_tokens = osl
                b.stop_conditions.ignore_eos = True
                stream = await engine.generate(b.to_dict())
                n = 0
                async for item in stream:
                    n += len(item.get("token_ids", []))
                return n

            async def burst(batch):
                for _ in range(WARMUP_BURSTS):
                    await asyncio.gather(*[run_one(p) for p in batch])
                best = 0.0
                for _ in range(TIMED_BURSTS):
                    t0 = time.perf_counter()
                    results = await asyncio.gather(
                        *[run_one(p) for p in batch]
                    )
                    best = max(
                        best, sum(results) / (time.perf_counter() - t0)
                    )
                return best

            tok_s = asyncio.run(burst(workload_prompts))
            m = engine.metrics()
            drafted = m["spec_draft_tokens"]
            # Per-ROW basis: a batched verify dispatch over N rows is N
            # row participations; emitted / device-dispatches would
            # conflate batch occupancy with speculation speedup (the
            # sim fit divides per-row ITL by this number).
            dispatches = m["spec_row_dispatches"]
            out.append(
                {
                    "metric": f"spec_decode_{MODEL}_isl{isl}_osl{osl}"
                    f"_{workload}_d{draft}",
                    "value": round(tok_s, 1),
                    "unit": "tok/s",
                    "vs_baseline": round(
                        tok_s / _roofline_tok_s(engine.params, slots), 4
                    ),
                    "workload": workload,
                    "spec": {
                        "mode": cfg.spec_mode,
                        "draft_len": draft,
                        "ngram": cfg.spec_ngram,
                    },
                    "draft_tokens": drafted,
                    "accepted_tokens": m["spec_accepted_tokens"],
                    "acceptance_rate": round(
                        m["spec_accepted_tokens"] / drafted, 4
                    )
                    if drafted
                    else None,
                    "tokens_per_dispatch": round(
                        m["spec_emitted_tokens"] / dispatches, 4
                    )
                    if dispatches
                    else None,
                    "decode_window": engine.cfg.decode_window,
                    "dispatch": _dispatch_stats(engine),
                    "anatomy": _anatomy_stats(engine),
                }
            )
            engine.stop()
    return out


def run_prefix_reuse(isl: int = 1024, osl: int = 16, concurrency: int = 8) -> dict:
    """TTFT with a warm shared prefix vs cold prompts.

    The reference's headline KV-reuse claims (BASELINE.md: 3x TTFT from
    KV-aware routing over cached prefixes, 40% from offload) rest on
    exactly this effect: a request whose prefix blocks are already in
    the pool skips their prefill. Here every request shares the first
    ~87% of the prompt; warm TTFT should approach the cost of
    prefilling only the distinct tail.
    """
    import asyncio

    from dynamo_exp_tpu.engine import EngineConfig
    from dynamo_exp_tpu.protocols.common import BackendInput

    _enable_compile_cache()
    mcfg = _preset(MODEL)
    cfg = EngineConfig(
        model=mcfg,
        max_decode_slots=concurrency,
        page_size=16,
        num_pages=concurrency * ((isl + osl) // 16 + 2) + 256,
        max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
        eos_token_ids=[],
        kv_dtype=_kv_dtype(),
        decode_window=8,
    )
    engine = _build_engine(cfg)
    rs = np.random.RandomState(0)
    shared = rs.randint(10, mcfg.vocab_size - 10, size=(isl * 7) // 8).tolist()
    tail = isl - len(shared)

    async def one(prompt):
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = osl
        b.stop_conditions.ignore_eos = True
        t0 = time.perf_counter()
        stream = await engine.generate(b.to_dict())
        async for item in stream:
            if item.get("token_ids"):
                return time.perf_counter() - t0
        return None

    async def measure():
        # Cold: all-distinct prompts (after compile warmup on other shapes).
        warm_prompt = rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
        await one(warm_prompt)  # compile
        cold = [
            await one(rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist())
            for _ in range(concurrency)
        ]
        # Warm: seed the shared prefix once, then same-prefix requests.
        await one(shared + rs.randint(10, mcfg.vocab_size - 10, size=tail).tolist())
        warm = [
            await one(
                shared + rs.randint(10, mcfg.vocab_size - 10, size=tail).tolist()
            )
            for _ in range(concurrency)
        ]
        # Stop inside the loop: engine callbacks scheduled during the
        # last responses must land on a live loop, not a closed one.
        engine.stop()
        return cold, warm

    cold, warm = asyncio.run(measure())
    p50 = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    return {
        "metric": f"prefix_reuse_ttft_{MODEL}_isl{isl}",
        "value": round(p50(cold) / p50(warm), 2),
        "unit": "x speedup",
        "vs_baseline": round((p50(cold) / p50(warm)) / 3.0, 4),  # ref: 3x
        "p50_ttft_cold_s": round(p50(cold), 3),
        "p50_ttft_warm_s": round(p50(warm), 3),
        "decode_window": engine.cfg.decode_window,
        "dispatch": _dispatch_stats(engine),
        "anatomy": _anatomy_stats(engine),
    }


def run_prefix_sweep(
    isl: int = 1024,
    osl: int = 32,
    concurrency: int = 8,
    ratios: tuple = (0.0, 0.5, 0.875),
) -> list:
    """Fleet-wide prefix sharing vs the private-copy baseline
    (docs/prefix_sharing.md) across a shared-prefix ratio axis.

    Each point fires one *concurrent* burst of ``concurrency`` requests
    whose prompts share the first ``ratio * isl`` tokens — the
    many-users-one-system-prompt shape — against a sharing engine and a
    ``prefix_sharing=False`` baseline, and reports HBM pages per request
    (resident-page high-water mark / requests), p50 TTFT, and the
    page-granular prefix-hit breakdown. Concurrent admission is the
    point: sharing must collapse pages even when every request arrives
    before the first one has prefilled (pending-fill attach).
    """
    import asyncio

    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.protocols.common import BackendInput

    _enable_compile_cache()
    mcfg = _preset(MODEL)
    ps = 16

    def build_engine(sharing: bool) -> TPUEngine:
        cfg = EngineConfig(
            model=mcfg,
            max_decode_slots=concurrency,
            page_size=ps,
            # Sized for the PRIVATE worst case so the baseline arm
            # measures pages, not preemption thrash.
            num_pages=concurrency * ((isl + osl) // ps + 2) + 64,
            max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
            eos_token_ids=[],
            kv_dtype=_kv_dtype(),
            decode_window=8,
            prefix_sharing=sharing,
        )
        return _build_engine(cfg)

    async def run_one(engine, prompt):
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = osl
        b.stop_conditions.ignore_eos = True
        t0 = time.perf_counter()
        stream = await engine.generate(b.to_dict())
        ttft = None
        async for item in stream:
            if item.get("token_ids") and ttft is None:
                ttft = time.perf_counter() - t0
        return ttft

    async def burst(engine, prompts):
        return await asyncio.gather(*[run_one(engine, p) for p in prompts])

    def arm(engine, warm_prompts, prompts) -> dict:
        # Per-ratio warm burst: a shared-prefix burst exercises suffix-
        # length prefill buckets the full-prompt warmup never compiled —
        # TTFT must measure steady state, not variant compiles. The warm
        # burst uses its own prefix, so the measured burst's hit counts
        # stay cold/honest.
        asyncio.run(burst(engine, warm_prompts))
        # High-water marks measured per burst: rebase to the quiesced
        # pool (previous bursts' pages are parked, not active/shared).
        engine.kv.peak_active_pages = engine.kv.active_pages
        engine.kv.peak_shared_pages = engine.kv.live_shared
        hits0 = dict(engine.kv.prefix_hits)
        cow0 = engine.kv.cow_copies
        ttfts = sorted(
            t for t in asyncio.run(burst(engine, prompts)) if t is not None
        )
        m = engine.metrics()
        return {
            "pages_per_request": round(
                engine.kv.peak_active_pages / max(len(prompts), 1), 2
            ),
            "p50_ttft_s": round(ttfts[len(ttfts) // 2], 3),
            "prefix_hits": {
                k: m[f"kv_prefix_hits_{k}"] - hits0[k]
                for k in ("shared", "restore", "miss")
            },
            "cow_copies": m["kv_cow_copies"] - cow0,
            "shared_pages_peak": engine.kv.peak_shared_pages,
        }

    rs = np.random.RandomState(0)
    shared_eng = build_engine(True)
    private_eng = build_engine(False)
    out = []
    # Compile warmup on both arms (distinct prompts: no sharing yet).
    warm = [
        rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
        for _ in range(concurrency)
    ]
    for eng in (shared_eng, private_eng):
        for _ in range(WARMUP_BURSTS):
            asyncio.run(burst(eng, warm))
    for ratio in ratios:
        prefix_len = int(isl * ratio) // ps * ps

        def ratio_prompts() -> list:
            prefix = rs.randint(
                10, mcfg.vocab_size - 10, size=prefix_len
            ).tolist()
            return [
                prefix
                + rs.randint(
                    10, mcfg.vocab_size - 10, size=isl - prefix_len
                ).tolist()
                for _ in range(concurrency)
            ]

        warm_prompts, prompts = ratio_prompts(), ratio_prompts()
        shared = arm(shared_eng, warm_prompts, prompts)
        private = arm(private_eng, warm_prompts, prompts)
        out.append(
            {
                "metric": (
                    f"prefix_sweep_{MODEL}_isl{isl}_c{concurrency}"
                    f"_r{ratio}"
                ),
                "value": shared["pages_per_request"],
                "unit": "pages/request",
                "shared_prefix_ratio": ratio,
                "vs_baseline": round(
                    shared["pages_per_request"]
                    / max(private["pages_per_request"], 1e-9),
                    4,
                ),
                "shared": shared,
                "private": private,
                "decode_window": shared_eng.cfg.decode_window,
                "dispatch": _dispatch_stats(shared_eng),
                "anatomy": _anatomy_stats(shared_eng),
            }
        )
    shared_eng.stop()
    private_eng.stop()
    return out


def run_coldstart_sweep(
    isl: int = 512, osl: int = 32, concurrency: int = 4
) -> list[dict]:
    """Cold vs warm boot: what an autoscaled instance pays between
    "worker add" and serving (docs/aot.md "Coldstart study").

    Three phases against one persistent compilation cache directory:

    1. **cold** — a fresh engine with an *empty* cache serves the probe
       burst; every variant compiles inline on the serving path, so its
       first-token and first-burst TTFTs carry the compile stalls (this
       also populates the cache, like the first instance of a fleet).
    2. **populate** — ``aot_compile`` fills the remainder of the
       lattice offline (the ``llmctl aot compile`` deployment step;
       untimed).
    3. **warm** — a fresh engine prewarns from the populated cache
       before accepting traffic, then serves the identical burst with
       zero compile misses.

    Each arm's line reports the components separately — ``boot_s``
    (engine build; weights are shared across arms, checkpoint load is
    arm-invariant), ``prewarm_s``, ``first_token_s`` (serving start →
    first emitted token), ``first_burst_ttft_p50_s``, and
    ``steady_ttft_p50_s`` — plus ``provision_s`` (= boot + prewarm +
    first token), which is what ``sim/fit.py`` feeds
    ``planner_hints()`` → ``SloTargets.provision_s``. The headline
    ``value`` is ``provision_s``; the summary line carries the
    cold/warm ratios. On XLA:CPU (fallback) compiles are cheap and the
    ratios are modest; on the real chip a variant compile is 20-40s and
    the cold arm's stalls dominate everything (the ``platform`` tag
    keeps the two regimes apart)."""
    import asyncio
    import tempfile

    import jax

    from dynamo_exp_tpu.aot import (
        aot_compile,
        enable_persistent_cache,
        manifest_for_engine,
    )
    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models.llama import init_params
    from dynamo_exp_tpu.protocols.common import BackendInput, SamplingOptions

    cache_dir = tempfile.mkdtemp(prefix="dynamo_coldstart_")
    enable_persistent_cache(cache_dir)
    mcfg = _preset(MODEL)

    def cfg() -> EngineConfig:
        return EngineConfig(
            model=mcfg,
            max_decode_slots=concurrency,
            page_size=16,
            num_pages=concurrency * ((isl + osl) // 16 + 2) + 64,
            max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
            eos_token_ids=[],
            kv_dtype=_kv_dtype(),
            decode_window=8,
        )

    # Checkpoint load is arm-invariant (and not what AOT optimizes):
    # share one weight init so the arms differ only in compile work.
    params = init_params(jax.random.PRNGKey(0), mcfg)
    jax.block_until_ready(params)
    rs = np.random.RandomState(0)

    def prompts() -> list[list[int]]:
        return [
            rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
            for _ in range(concurrency)
        ]

    async def burst(engine, batch) -> tuple[float | None, list[float]]:
        """Serve one mixed burst (alternating greedy / seeded rows);
        returns (wall time of the burst's FIRST emitted token, all
        per-request TTFTs)."""
        first: list[float] = []
        ttfts: list[float] = []

        async def one(i: int, prompt):
            b = BackendInput(token_ids=prompt)
            b.stop_conditions.max_tokens = osl
            b.stop_conditions.ignore_eos = True
            if i % 2:
                b.sampling_options = SamplingOptions(
                    seed=i, temperature=0.8
                )
            t0 = time.perf_counter()
            stream = await engine.generate(b.to_dict())
            async for item in stream:
                if item.get("token_ids"):
                    now = time.perf_counter()
                    first.append(now)
                    ttfts.append(now - t0)
                    break
            async for _ in stream:
                pass

        await asyncio.gather(*[one(i, p) for i, p in enumerate(batch)])
        return (min(first) if first else None), sorted(ttfts)

    def arm(prewarmed: bool) -> dict:
        t0 = time.perf_counter()
        engine = TPUEngine(cfg(), params=params, seed=0)
        manifest = manifest_for_engine(engine)
        boot_s = time.perf_counter() - t0
        prewarm_s = 0.0
        if prewarmed:
            report = engine.prewarm(manifest)
            prewarm_s = report.seconds
        engine.start()
        serving_at = time.perf_counter()
        first_at, ttfts = asyncio.run(burst(engine, prompts()))
        first_token_s = (
            first_at - serving_at if first_at is not None else None
        )
        _, steady = asyncio.run(burst(engine, prompts()))
        m = engine.metrics()
        disp = m["dispatch"]["ragged"]
        provision_s = boot_s + prewarm_s + (first_token_s or 0.0)
        point = {
            "metric": (
                f"coldstart_{MODEL}_isl{isl}_osl{osl}_c{concurrency}_"
                f"{'warm' if prewarmed else 'cold'}"
            ),
            # Headline: provisioned -> first token (the serving-path
            # stall AOT removes). The full worker-add -> first-token
            # delay (boot + prewarm + first token) rides as
            # ``provision_s`` — the sample sim/fit.py feeds the
            # planner, warm and cold distinguished by ``prewarmed``.
            "value": round(first_token_s, 3)
            if first_token_s is not None
            else None,
            "unit": "s provisioned-to-first-token",
            "provision_s": round(provision_s, 3),
            "boot_s": round(boot_s, 3),
            "prewarm_s": round(prewarm_s, 3),
            "first_token_s": round(first_token_s, 3)
            if first_token_s is not None
            else None,
            "first_burst_ttft_p50_s": round(
                ttfts[len(ttfts) // 2], 3
            )
            if ttfts
            else None,
            "steady_ttft_p50_s": round(steady[len(steady) // 2], 3)
            if steady
            else None,
            "prewarmed": prewarmed,
            "manifest_hash": manifest.hash(),
            "prewarmed_variants": m["prewarmed_variants"],
            "compiled_ragged_variants": m["compiled_ragged_variants"],
            "ragged_compile_misses": disp["compile_misses"],
            "ragged_compile_total_s": disp["compile_total_s"],
            "decode_window": engine.cfg.decode_window,
            "dispatch": _dispatch_stats(engine),
            "anatomy": _anatomy_stats(engine),
        }
        engine.stop()
        return point

    cold = arm(False)
    # Deployment's offline populate step (llmctl aot compile): fill the
    # lattice entries cold traffic never walked. Untimed; the engine
    # (and its full KV pool) is dropped before the warm arm boots so
    # the warm measurement doesn't run under doubled HBM residency.
    populate = TPUEngine(cfg(), params=params, seed=0)
    aot_compile(populate, cache_dir=cache_dir)
    del populate
    warm = arm(True)

    def ratio(a, b):
        return round(a / b, 2) if a and b else None

    summary = {
        "metric": f"coldstart_{MODEL}_isl{isl}_osl{osl}_c{concurrency}"
        "_speedup",
        "value": ratio(cold["first_token_s"], warm["first_token_s"]),
        "unit": "x provisioned-to-first-token",
        "first_burst_ttft_speedup": ratio(
            cold["first_burst_ttft_p50_s"], warm["first_burst_ttft_p50_s"]
        ),
        "full_provision_speedup": ratio(
            cold["provision_s"], warm["provision_s"]
        ),
        "cold_provision_s": cold["provision_s"],
        "warm_provision_s": warm["provision_s"],
        "prewarmed": True,
        "manifest_hash": warm["manifest_hash"],
        "compile_cache_dir": cache_dir,
    }
    return [cold, warm, summary]


def run_restart_sweep(
    isl: int = 512, osl: int = 32, concurrency: int = 4
) -> list[dict]:
    """Cold-boot vs warm-cache restart TTFT (docs/fault_tolerance.md
    "Durable KV & corruption containment").

    Three phases against one durable G3 store directory:

    1. **seed** — an engine with the store serves a shared-prefix
       burst, then a churn burst large enough to evict the parked
       prefix blocks into the host tier; ``stop()`` drains the host
       tier through the G3 writer (the crash-consistent demotion
       path), leaving the prefix on disk.
    2. **cold** — a fresh engine over an *empty* store serves the
       identical shared-prefix probe: nothing to adopt, the full
       prefix re-prefills (the restart-without-durability baseline).
    3. **warm** — a fresh engine over the seeded store ``boot_scan``s,
       re-adopts the surviving pages, and serves the same probe: the
       shared prefix re-attaches from G3 (checksum-verified) and only
       the per-request suffix prefills.

    Both arms run the same compile warmup first, so the TTFT delta is
    the shared-prefix prefill cost the durable tier removes — the
    restart-recovery headline. Lines carry ``prewarmed`` (store, not
    compile, prewarming here) and the per-arm G3 counters
    (``kv_prefix_hits_persist``, ``kv_store_adopted``) as proof the
    warm arm actually restored rather than re-prefilled."""
    import asyncio
    import shutil
    import tempfile

    import jax

    from dynamo_exp_tpu.aot import manifest_for_engine
    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models.llama import init_params
    from dynamo_exp_tpu.protocols.common import BackendInput

    _enable_compile_cache()
    mcfg = _preset(MODEL)
    ps = 16
    prefix_len = (isl // 2) // ps * ps
    num_pages = concurrency * ((isl + osl) // ps + 2) + 8

    def cfg(store_dir: str) -> EngineConfig:
        return EngineConfig(
            model=mcfg,
            max_decode_slots=concurrency,
            page_size=ps,
            # Tight pool: the seed arm's churn burst must evict the
            # parked prefix into the host tier for stop() to drain.
            num_pages=num_pages,
            max_model_len=max(512, ((isl + osl) // 256 + 2) * 256),
            eos_token_ids=[],
            kv_dtype=_kv_dtype(),
            decode_window=8,
            prefix_sharing=True,
            host_cache_pages=num_pages * 4,
            kv_store_dir=store_dir,
        )

    params = init_params(jax.random.PRNGKey(0), mcfg)
    jax.block_until_ready(params)
    rs = np.random.RandomState(0)

    def distinct(n: int) -> list[list[int]]:
        return [
            rs.randint(10, mcfg.vocab_size - 10, size=isl).tolist()
            for _ in range(n)
        ]

    # One fixed prompt set across all three phases: identical tokens
    # mean identical chained block hashes, so the warm arm's G3 match
    # is exactly the seed arm's demoted prefix.
    warm_prompts = distinct(concurrency)

    def shared_burst() -> list[list[int]]:
        p = rs.randint(10, mcfg.vocab_size - 10, size=prefix_len).tolist()
        return [
            p
            + rs.randint(
                10, mcfg.vocab_size - 10, size=isl - prefix_len
            ).tolist()
            for _ in range(concurrency)
        ]

    # A second, never-stored shared prefix for warmup: the probe's
    # suffix-length prefill bucket must compile during warmup in BOTH
    # arms, or the warm arm's G3-shortened prefill pays a variant
    # compile the cold arm's full-prompt path never hits.
    probe_prompts, suffix_warm_prompts = shared_burst(), shared_burst()
    churn_prompts = distinct(2 * concurrency)

    async def run_one(engine, prompt):
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = osl
        b.stop_conditions.ignore_eos = True
        t0 = time.perf_counter()
        stream = await engine.generate(b.to_dict())
        ttft = None
        async for item in stream:
            if item.get("token_ids") and ttft is None:
                ttft = time.perf_counter() - t0
        return ttft

    async def burst(engine, prompts):
        return await asyncio.gather(*[run_one(engine, p) for p in prompts])

    seeded_store = tempfile.mkdtemp(prefix="dynamo_restart_g3_")
    empty_store = tempfile.mkdtemp(prefix="dynamo_restart_empty_")

    # Phase 1: seed the store, then churn the prefix off-device and
    # drain it to disk through the stop() path.
    engine = TPUEngine(cfg(seeded_store), params=params, seed=0)
    manifest = manifest_for_engine(engine)
    engine.start()
    asyncio.run(burst(engine, warm_prompts))
    asyncio.run(burst(engine, probe_prompts))
    for i in range(0, len(churn_prompts), concurrency):
        asyncio.run(burst(engine, churn_prompts[i : i + concurrency]))
    engine.stop()
    seeded_pages = engine.g3_store.resident if engine.g3_store else 0

    def arm(store_dir: str, prewarmed: bool) -> dict:
        engine = TPUEngine(cfg(store_dir), params=params, seed=0)
        adopted = engine.g3_store.adopted if engine.g3_store else 0
        engine.start()
        asyncio.run(burst(engine, warm_prompts))  # full-prompt warmup
        asyncio.run(burst(engine, suffix_warm_prompts))  # suffix bucket
        ttfts = sorted(
            t
            for t in asyncio.run(burst(engine, probe_prompts))
            if t is not None
        )
        m = engine.metrics()
        point = {
            "metric": (
                f"restart_{MODEL}_isl{isl}_osl{osl}_c{concurrency}_"
                f"{'warm' if prewarmed else 'cold'}"
            ),
            "value": round(ttfts[len(ttfts) // 2], 3) if ttfts else None,
            "unit": "s probe-burst ttft p50",
            "ttft_p50_s": round(ttfts[len(ttfts) // 2], 3)
            if ttfts
            else None,
            "ttft_max_s": round(ttfts[-1], 3) if ttfts else None,
            "prefix_tokens": prefix_len,
            "prewarmed": prewarmed,
            "manifest_hash": manifest.hash(),
            "kv_store_adopted": adopted,
            "kv_prefix_hits_persist": m.get("kv_prefix_hits_persist", 0),
            "kv_store_checksum_failures": m.get(
                "kv_store_checksum_failures", 0
            ),
            "dispatch": _dispatch_stats(engine),
            "anatomy": _anatomy_stats(engine),
        }
        engine.stop()
        return point

    cold = arm(empty_store, False)
    warm = arm(seeded_store, True)

    def ratio(a, b):
        return round(a / b, 2) if a and b else None

    summary = {
        "metric": f"restart_{MODEL}_isl{isl}_osl{osl}_c{concurrency}"
        "_speedup",
        "value": ratio(cold["ttft_p50_s"], warm["ttft_p50_s"]),
        "unit": "x cold/warm probe ttft p50",
        "seeded_store_pages": seeded_pages,
        "warm_adopted_pages": warm["kv_store_adopted"],
        "warm_persist_hits": warm["kv_prefix_hits_persist"],
        "cold_ttft_p50_s": cold["ttft_p50_s"],
        "warm_ttft_p50_s": warm["ttft_p50_s"],
        "prewarmed": True,
        "manifest_hash": manifest.hash(),
    }
    shutil.rmtree(empty_store, ignore_errors=True)
    shutil.rmtree(seeded_store, ignore_errors=True)
    return [cold, warm, summary]


def run_reclaim_sweep(
    seed: int = 11,
    spot_fraction: float = 0.5,
    grace_s: float = 4.0,
    duration_s: float = 240.0,
    instances: int = 4,
    reclaim_rates: tuple[float, ...] = (0.0, 2.0, 6.0, 12.0),
) -> list[dict]:
    """Spot-reclamation economics: goodput, migrated-vs-failover split,
    p99 TTFT, and billed chip-seconds per reclaim rate
    (docs/fault_tolerance.md "Spot reclamation & live migration").

    Sim-driven (no chip): a fixed fleet with ``spot_fraction`` of its
    instances on spot capacity serves one deterministic ramp while
    reclaim notices arrive at each swept rate, each with ``grace_s`` of
    warning. Every notice runs the REAL ``runtime.reclaim.plan_triage``
    deadline planner, so the migrated fraction per line is the live
    triage policy's hit rate at that grace window, not a modeling knob.

    The first line is the all-on-demand control (``spot_fraction=0``,
    no reclaims, full price); spot lines report ``vs_baseline`` as
    goodput relative to it. The headline is the pair (``vs_baseline``,
    ``goodput_per_billed_chip_s``): a healthy triage plane holds
    goodput near the control while billed chip-seconds shrink by the
    spot discount — and rising ``reclaim_failovers`` with falling
    ``migrated_fraction`` at high rates shows exactly where the grace
    deadline stops covering the transfer bill."""
    from dynamo_exp_tpu.sim.cluster import ClusterSim, SimConfig
    from dynamo_exp_tpu.sim.workload import ramp_workload

    def one(rate: float, spot: float, label: str) -> dict:
        cfg = SimConfig(
            seed=seed,
            slots_per_instance=8,
            pages_per_instance=144,
            page_size=16,
            max_inflight=16,
            shed_watermark=12,
            admission_per_instance=True,
            initial_instances=instances,
            provision_s=5.0,
            spot_fraction=spot,
            reclaim_rate_per_min=rate,
            reclaim_grace_s=grace_s,
            record_events=False,
        )
        wl = ramp_workload(
            seed,
            duration_s=duration_s,
            rps_start=2.0,
            rps_end=8.0,
            prompt_len=(64, 256),
            max_tokens=(16, 64),
        )
        rep = ClusterSim(cfg, wl).run()
        moved = rep.reclaim_migrated + rep.reclaim_failovers
        return {
            "metric": f"reclaim_sweep_spot{int(spot * 100)}"
            f"_g{grace_s:g}_{label}",
            "value": rep.goodput_tok_s,
            "unit": "goodput tok/s",
            "reclaim_rate_per_min": rate,
            "spot_fraction": spot,
            "grace_s": grace_s,
            "reclaims": rep.reclaims,
            "reclaim_migrated": rep.reclaim_migrated,
            "reclaim_failovers": rep.reclaim_failovers,
            "reclaim_migrated_pages": rep.reclaim_migrated_pages,
            "migrated_fraction": round(rep.reclaim_migrated / moved, 4)
            if moved
            else None,
            "ttft_p99_s": rep.ttft_p99_s,
            "submitted": rep.submitted,
            "completed": rep.completed,
            "preemptions": rep.preemptions,
            "chip_seconds": rep.chip_seconds,
            "billed_chip_seconds": rep.billed_chip_seconds,
            "goodput_per_billed_chip_s": round(
                rep.completed_tokens / rep.billed_chip_seconds, 2
            )
            if rep.billed_chip_seconds
            else None,
        }

    base = one(0.0, 0.0, "ondemand")
    base["vs_baseline"] = 1.0
    out = [base]
    for rate in reclaim_rates:
        point = one(rate, spot_fraction, f"r{rate:g}")
        point["vs_baseline"] = (
            round(point["value"] / base["value"], 4)
            if base["value"]
            else None
        )
        out.append(point)
    return out


def _fall_back_to_cpu(reason: str) -> str:
    """Pin this process (and its children) to the XLA CPU backend.
    Env var for anything imported later, config update in case a
    sitecustomize already registered an accelerator plugin as default
    (the same two-step pin tier-1's conftest uses)."""
    import os
    import sys

    print(f"bench: {reason}; falling back to JAX_PLATFORMS=cpu", file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def _probe_device(timeout_s: float = 180.0) -> str:
    """Probe the accelerator backend in a subprocess — jax.devices()
    against a dead TPU tunnel blocks indefinitely, which would otherwise
    hang the whole bench run. Unreachable (timeout or init error) is not
    fatal: fall back to the CPU backend so the perf trajectory keeps
    recording (each JSON line is tagged with the platform actually
    used). Returns that platform name."""
    import os
    import subprocess
    import sys

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return _fall_back_to_cpu("JAX_PLATFORMS=cpu requested")
    timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", timeout_s))
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print(jax.devices()[0].platform)",
            ],
            timeout=timeout_s,
            check=True,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        return _fall_back_to_cpu(
            f"accelerator backend unreachable (device init exceeded "
            f"{timeout_s:.0f}s) — TPU tunnel down?"
        )
    except subprocess.CalledProcessError as e:
        return _fall_back_to_cpu(
            f"device init failed: {e.stderr.decode(errors='replace')[-500:]}"
        )
    lines = out.stdout.decode(errors="replace").strip().splitlines()
    platform = lines[-1].strip() if lines else ""
    if platform not in ("cpu", "tpu", "gpu", "cuda", "rocm"):
        # Probe exited 0 but reported nothing recognizable: an
        # unverified backend must not get the full TPU-shape run —
        # that's the hours-long "dead bench" this fallback prevents.
        return _fall_back_to_cpu(
            f"device probe returned unrecognized platform {platform!r}"
        )
    return platform


def main() -> None:
    global MODEL
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--sweep",
        action="store_true",
        help="reference-shape sweep (ISL 3000 / OSL 150, concurrency 1..32)",
    )
    ap.add_argument(
        "--prefix-reuse",
        action="store_true",
        help="warm-prefix vs cold TTFT (the KV-reuse headline claim)",
    )
    ap.add_argument(
        "--occupancy-sweep",
        action="store_true",
        help="tok/s at 1/2/4/8 active sequences of 8 slots (compacted "
        "decode proportionality curve) plus a mixed prefill+decode "
        "axis: late prompts injected mid-decode, reporting late-join "
        "TTFT and compiled-ragged-variant counts per line",
    )
    ap.add_argument(
        "--overload-sweep",
        action="store_true",
        help="goodput / shed rate / p99 TTFT / preemption count per "
        "burst level against a pressure-sized pool (graceful "
        "degradation curve)",
    )
    ap.add_argument(
        "--spec-sweep",
        action="store_true",
        help="speculative decoding tok/s + acceptance across draft "
        "lengths {0,2,4,8} on prefix-repetitive vs random workloads",
    )
    ap.add_argument(
        "--prefix-sweep",
        action="store_true",
        help="HBM pages/request, TTFT, and prefix-hit breakdown across "
        "a shared-prefix ratio axis, sharing vs private-copy baseline",
    )
    ap.add_argument(
        "--coldstart-sweep",
        action="store_true",
        help="cold vs AOT-warm boot: provision-to-first-token, "
        "first-burst TTFT and compile-stall attribution per arm "
        "against one persistent compile cache (docs/aot.md)",
    )
    ap.add_argument(
        "--restart-sweep",
        action="store_true",
        help="cold-boot vs durable-G3 warm-cache restart: shared-prefix "
        "probe TTFT per arm against one seeded store directory, with "
        "adopted-page / persist-hit proof (docs/fault_tolerance.md)",
    )
    ap.add_argument(
        "--reclaim-sweep",
        action="store_true",
        help="spot-reclamation economics (sim-driven): goodput, "
        "migrated-vs-failover split, p99 TTFT, and billed "
        "chip-seconds per reclaim rate vs an all-on-demand control",
    )
    ap.add_argument(
        "--prewarm",
        action="store_true",
        help="prewarm every bench engine from the compile lattice "
        "before measuring (lines are tagged prewarmed=true)",
    )
    ap.add_argument(
        "--model",
        default=None,
        help=f"preset name (default {MODEL}; {CPU_MODEL} on CPU fallback)",
    )
    # Default-point shape overrides (smoke tests run a tiny point; the
    # metric name carries the shape, so overridden runs stay labeled).
    # None = not given: the default resolves per platform after the
    # probe, but an explicit flag always wins, even on CPU fallback.
    ap.add_argument("--isl", type=int, default=None)
    ap.add_argument("--osl", type=int, default=None)
    ap.add_argument("--concurrency", type=int, default=None)
    args = ap.parse_args()
    platform = _probe_device()
    if platform == "cpu":
        global CPU_FALLBACK, WARMUP_BURSTS, TIMED_BURSTS
        CPU_FALLBACK = True
        WARMUP_BURSTS = TIMED_BURSTS = 1
    MODEL = args.model or (CPU_MODEL if platform == "cpu" else MODEL)
    if args.isl is None:
        args.isl = CPU_ISL if platform == "cpu" else ISL
    if args.osl is None:
        args.osl = CPU_OSL if platform == "cpu" else OSL
    if args.concurrency is None:
        args.concurrency = CPU_CONCURRENCY if platform == "cpu" else CONCURRENCY

    if args.prewarm:
        global PREWARM
        PREWARM = True

    def emit(point: dict) -> None:
        # Every line carries the warm/cold tag + manifest hash (set by
        # _build_engine; coldstart lines carry their own per-arm
        # values, which win) so sim/fit.py can split provision samples.
        print(
            json.dumps(dict(LINE_TAGS) | point | {"platform": platform}),
            flush=True,
        )

    cpu = platform == "cpu"
    if args.reclaim_sweep:
        # Sim-driven: numbers are host-independent, so lines carry
        # platform="sim" — chip and CPU-fallback captures of this
        # sweep stay comparable in `llmctl bench compare` instead of
        # being skipped as a platform mismatch.
        for point in run_reclaim_sweep(**(CPU_RECLAIM_KW if cpu else {})):
            print(
                json.dumps(dict(LINE_TAGS) | point | {"platform": "sim"}),
                flush=True,
            )
        return
    if args.coldstart_sweep:
        for point in run_coldstart_sweep(**(CPU_COLDSTART_KW if cpu else {})):
            emit(point)
        return
    if args.restart_sweep:
        for point in run_restart_sweep(**(CPU_RESTART_KW if cpu else {})):
            emit(point)
        return
    if args.sweep:
        s_isl = CPU_SWEEP_ISL if cpu else SWEEP_ISL
        s_osl = CPU_SWEEP_OSL if cpu else SWEEP_OSL
        for c in CPU_SWEEP_CONCURRENCY if cpu else SWEEP_CONCURRENCY:
            emit(run_point(s_isl, s_osl, c))
    elif args.occupancy_sweep:
        kw = (
            dict(CPU_SWEEP_KW, pressure_multiples=CPU_PRESSURE_MULTIPLES)
            if cpu
            else {}
        )
        for point in run_occupancy_sweep(**kw):
            emit(point)
    elif args.overload_sweep:
        kw = (
            dict(CPU_SWEEP_KW, burst_levels=CPU_OVERLOAD_BURSTS) if cpu else {}
        )
        for point in run_overload_sweep(**kw):
            emit(point)
    elif args.spec_sweep:
        for point in run_spec_sweep(**(CPU_SPEC_KW if cpu else {})):
            emit(point)
    elif args.prefix_sweep:
        for point in run_prefix_sweep(**(CPU_PREFIX_SWEEP_KW if cpu else {})):
            emit(point)
    elif args.prefix_reuse:
        emit(run_prefix_reuse(**(CPU_PREFIX_KW if cpu else {})))
    else:
        emit(run_point(args.isl, args.osl, args.concurrency))


if __name__ == "__main__":
    main()
