"""Engine-facing request/response types shared by all frontends.

Capability parity with the reference's common protocol layer
(``/root/reference/lib/llm/src/protocols/common.rs``): stop conditions,
sampling options, the preprocessed ``BackendInput`` handed to engines, and
the per-step ``LLMEngineOutput`` engines stream back.
"""

from __future__ import annotations

import enum
from typing import Any

from pydantic import BaseModel, Field


PRIORITY_LOW = 0
PRIORITY_NORMAL = 1
PRIORITY_HIGH = 2

_PRIORITY_NAMES = {
    "low": PRIORITY_LOW,
    "normal": PRIORITY_NORMAL,
    "high": PRIORITY_HIGH,
}
_NAME_BY_PRIORITY = {v: k for k, v in _PRIORITY_NAMES.items()}


def parse_priority(raw) -> int:
    """Normalize a request's priority class to 0/1/2 (low/normal/high).

    Accepts the class names (case-insensitive) or their integers;
    ``None`` means ``normal``. Anything else raises ``ValueError`` (the
    HTTP layer maps it to 400) — a client that *tried* to prioritize
    deserves to know the spelling was wrong, not a silent ``normal``."""
    if raw is None:
        return PRIORITY_NORMAL
    if isinstance(raw, bool):  # bool is an int subclass; reject explicitly
        raise ValueError(f"invalid priority: {raw!r}")
    if isinstance(raw, int):
        if raw in _NAME_BY_PRIORITY:
            return raw
        raise ValueError(
            f"invalid priority: {raw!r} (expected 0..2 or low/normal/high)"
        )
    if isinstance(raw, str):
        name = raw.strip().lower()
        if name in _PRIORITY_NAMES:
            return _PRIORITY_NAMES[name]
        if name.lstrip("-").isdigit():
            return parse_priority(int(name))
    raise ValueError(
        f"invalid priority: {raw!r} (expected 0..2 or low/normal/high)"
    )


def priority_name(priority: int) -> str:
    return _NAME_BY_PRIORITY.get(priority, str(priority))


class FinishReason(str, enum.Enum):
    EOS = "eos"
    STOP = "stop"
    LENGTH = "length"
    CANCELLED = "cancelled"
    ERROR = "error"

    def to_openai(self) -> str:
        return {
            FinishReason.EOS: "stop",
            FinishReason.STOP: "stop",
            FinishReason.LENGTH: "length",
            FinishReason.CANCELLED: "stop",
            FinishReason.ERROR: "error",
        }[self]


class StopConditions(BaseModel):
    """When to stop generating."""

    max_tokens: int | None = None
    stop: list[str] = Field(default_factory=list)  # hidden stop strings
    stop_token_ids: list[int] = Field(default_factory=list)
    min_tokens: int | None = None
    ignore_eos: bool = False

    def apply_defaults(self, max_tokens_default: int | None) -> None:
        if self.max_tokens is None:
            self.max_tokens = max_tokens_default


class SamplingOptions(BaseModel):
    """How to pick the next token."""

    n: int = 1
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    frequency_penalty: float | None = None
    presence_penalty: float | None = None
    repetition_penalty: float | None = None
    seed: int | None = None
    logprobs: int | None = None


class BackendInput(BaseModel):
    """The fully preprocessed request handed to an execution engine:
    token ids in, token ids out. This is the seam between the serving
    stack and any engine implementation (TPU, echo, remote)."""

    token_ids: list[int]
    stop_conditions: StopConditions = Field(default_factory=StopConditions)
    sampling_options: SamplingOptions = Field(default_factory=SamplingOptions)
    annotations: list[str] = Field(default_factory=list)
    # Router hint: estimated prefix-cache overlap blocks on the chosen worker.
    estimated_prefix_hit_num_blocks: int | None = None
    # Disaggregation: set when a remote prefill worker already computed the
    # prompt's KV; the decode engine skips prefill for those blocks.
    remote_prefill: dict[str, Any] | None = None
    # Resumable streams: this request is a mid-stream failover
    # continuation and the last ``resume_offset`` entries of
    # ``token_ids`` are journaled *completion* tokens being re-prefilled,
    # not prompt. Engines treat the request normally (one batched prefill
    # over the whole sequence); the field marks the re-prefill hop for
    # telemetry and accounting — the journaling router owns usage fixup.
    resume_offset: int | None = None
    # Admission-control priority class (0=low, 1=normal, 2=high). The
    # edge sheds low first under load; the engine preempts the
    # lowest-priority ACTIVE sequence first under KV pressure.
    priority: int = 1

    def to_dict(self) -> dict:
        return self.model_dump(exclude_none=True)


class LLMEngineOutput(BaseModel):
    """One streamed step from an engine (token-level, pre-detokenization)."""

    token_ids: list[int] = Field(default_factory=list)
    # Engines that do their own detokenization may set text directly.
    text: str | None = None
    cum_log_probs: float | None = None
    # Per-token logprobs, aligned with token_ids (present only when the
    # request asked): chosen-token logprob, and the top-N alternatives
    # as {token_id: logprob} (N = the request's top_logprobs).
    logprobs: list[float] | None = None
    top_logprobs: list[dict[int, float]] | None = None
    finish_reason: FinishReason | None = None
    # Usage accounting, set on the final frame.
    prompt_tokens: int | None = None
    completion_tokens: int | None = None

    def to_dict(self) -> dict:
        return self.model_dump(exclude_none=True)

    @classmethod
    def from_dict(cls, d: dict) -> "LLMEngineOutput":
        return cls.model_validate(d)
