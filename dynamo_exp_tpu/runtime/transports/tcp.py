"""TCP request plane: streaming request/response between processes.

The reference splits request push (NATS subject) from response delivery
(a raw TCP stream registered back to the caller —
``/root/reference/lib/runtime/src/pipeline/network/tcp/server.rs:74-615``,
``egress/addressed_router.rs:85-140``). Since this framework does its own
instance selection client-side (``push_router.py`` over discovery), we
collapse both planes into one hop: the client connects straight to the
chosen worker's TCP server and the response frames stream back on the
same socket. One fewer network hop than the reference per request, same
capabilities:

- two-part framing (header + payload, ``codec.py``);
- early errors ride an ERROR frame (the reference's
  ``ResponseStreamPrologue``);
- upstream ``ControlMessage``-style cancellation: the client writes
  CONTROL {stop|kill} frames; a dropped client connection kills the
  request context (the reference's client-disconnect handling,
  ``http/service/openai.rs:433``);
- graceful drain: a closing endpoint stops accepting and waits for
  inflight requests (``ingress/push_endpoint.rs:45-111``).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import weakref
from typing import AsyncIterator

from ...telemetry import (
    TraceContext,
    attach as trace_attach,
    detach as trace_detach,
    get_telemetry,
    wire_headers,
)
from ..engine import AsyncEngineContext
from .base import (
    Handler,
    InstanceInfo,
    RequestPlane,
    ServedEndpoint,
    StatsHandler,
)
from .codec import MsgType, TwoPartMessage, read_message, write_message

logger = logging.getLogger(__name__)


class _Served(ServedEndpoint):
    def __init__(self, plane: "TcpRequestPlane", instance_id: int):
        self._plane = plane
        self._instance_id = instance_id

    async def close(self) -> None:
        entry = self._plane._handlers.pop(self._instance_id, None)
        if entry is not None:
            _, _, inflight = entry
            while inflight[0] > 0:
                await asyncio.sleep(0.005)


class TcpRequestPlane(RequestPlane):
    """One TCP listener per process serves every endpoint the process
    hosts; requests carry the target instance_id in the header."""

    def __init__(self, bind_host: str = "127.0.0.1", bind_port: int = 0):
        self.bind_host = bind_host
        self.bind_port = bind_port
        self._server: asyncio.AbstractServer | None = None
        self._handlers: dict[int, tuple[Handler, StatsHandler | None, list[int]]] = {}

    async def _ensure_server(self) -> None:
        if self._server is None:
            self._server = await asyncio.start_server(
                self._handle_conn, self.bind_host, self.bind_port
            )
            self.bind_port = self._server.sockets[0].getsockname()[1]
            logger.info(
                "tcp request plane listening on %s:%d", self.bind_host, self.bind_port
            )

    @property
    def address(self) -> str:
        return f"{self.bind_host}:{self.bind_port}"

    # ------------------------------------------------------------- serving
    async def serve(
        self,
        info: InstanceInfo,
        handler: Handler,
        stats_handler: StatsHandler | None = None,
    ) -> ServedEndpoint:
        await self._ensure_server()
        info.transport = "tcp"
        info.transport_address = self.address
        self._handlers[info.instance_id] = (handler, stats_handler, [0])
        return _Served(self, info.instance_id)

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            msg = await read_message(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        try:
            if msg.msg_type == MsgType.STATS:
                await self._handle_stats(msg, writer)
            elif msg.msg_type == MsgType.REQUEST:
                await self._handle_request(msg, reader, writer)
            else:
                await write_message(
                    writer,
                    TwoPartMessage(
                        MsgType.ERROR, {"message": f"unexpected {msg.msg_type}"}
                    ),
                )
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _handle_stats(
        self, msg: TwoPartMessage, writer: asyncio.StreamWriter
    ) -> None:
        entry = self._handlers.get(msg.header.get("instance_id", 0))
        if entry is None:
            await write_message(
                writer, TwoPartMessage(MsgType.ERROR, {"message": "no such instance"})
            )
            return
        _, stats_handler, inflight = entry
        stats = {"inflight": inflight[0]}
        if stats_handler is not None:
            stats.update(stats_handler())
        await write_message(writer, TwoPartMessage(MsgType.STATS, stats))

    async def _handle_request(
        self,
        msg: TwoPartMessage,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        instance_id = msg.header.get("instance_id", 0)
        entry = self._handlers.get(instance_id)
        if entry is None:
            # Prologue-style early error: instance not served here.
            await write_message(
                writer,
                TwoPartMessage(
                    MsgType.ERROR, {"message": f"instance {instance_id} not here"}
                ),
            )
            return
        handler, _, inflight = entry
        request = json.loads(msg.payload) if msg.payload else {}
        context = AsyncEngineContext(request_id=msg.header.get("request_id"))
        # Deadline propagation: the caller ships its *remaining* budget
        # (not an absolute timestamp), so host clock skew can't shrink or
        # grow the window. An already-expired request is refused before
        # the handler runs — the remote stage must not waste work on it.
        timeout_s = msg.header.get("timeout_s")
        if timeout_s is not None:
            context.start_timeout(float(timeout_s))
        if context.deadline_expired:
            get_telemetry().deadline_exceeded.labels("request_plane").inc()
            await write_message(
                writer,
                TwoPartMessage(
                    MsgType.ERROR,
                    {"message": f"deadline exceeded for request {context.id}"},
                ),
            )
            return
        # Cross-process trace continuation: the caller's trace context
        # rides the request header; adopt it so every span/log emitted
        # while handling joins the caller's trace.
        trace_token = trace_attach(TraceContext.from_wire(msg.header.get("trace")))
        inflight[0] += 1

        # Control reader: stop/kill frames, and connection-drop => kill.
        async def _control() -> None:
            try:
                while True:
                    cmsg = await read_message(reader)
                    if cmsg.msg_type == MsgType.CONTROL:
                        if cmsg.header.get("op") == "kill":
                            context.kill()
                        else:
                            context.stop_generating()
            except (asyncio.IncompleteReadError, ConnectionError):
                context.kill()

        control_task = asyncio.ensure_future(_control())
        try:
            agen = handler(request, context)
            async for frame in agen:
                if context.is_killed:
                    with contextlib.suppress(Exception):
                        await agen.aclose()
                    break
                await write_message(
                    writer, TwoPartMessage(MsgType.FRAME, {}, json.dumps(frame).encode())
                )
            if not context.is_killed:
                await write_message(writer, TwoPartMessage(MsgType.COMPLETE, {}))
        except (ConnectionError, asyncio.IncompleteReadError):
            context.kill()
        except Exception as e:  # noqa: BLE001 - handler errors go in-band
            logger.exception("handler failed for instance %d", instance_id)
            with contextlib.suppress(ConnectionError):
                await write_message(
                    writer,
                    TwoPartMessage(MsgType.ERROR, {"message": f"{type(e).__name__}: {e}"}),
                )
        finally:
            trace_detach(trace_token)
            inflight[0] -= 1
            control_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await control_task

    # ------------------------------------------------------------- client
    async def request_stream(
        self,
        instance: InstanceInfo,
        request: dict,
        context: AsyncEngineContext,
    ) -> AsyncIterator[dict]:
        if instance.transport != "tcp" or not instance.transport_address:
            raise ConnectionError(
                f"instance {instance.instance_id} has no tcp address"
            )
        host, _, port = instance.transport_address.rpartition(":")
        try:
            reader, writer = await asyncio.open_connection(host, int(port))
        except OSError as e:
            raise ConnectionError(
                f"connect to {instance.transport_address} failed: {e}"
            ) from e
        header = {"instance_id": instance.instance_id, "request_id": context.id}
        trace = wire_headers()
        if trace:
            header["trace"] = trace
        remaining = context.time_remaining()
        if remaining is not None:
            header["timeout_s"] = max(remaining, 0.0)
        await write_message(
            writer,
            TwoPartMessage(MsgType.REQUEST, header, json.dumps(request).encode()),
        )

        # Forward local stop/kill upstream as CONTROL frames.
        async def _forward_control() -> None:
            with contextlib.suppress(ConnectionError, OSError):
                await context.stopped()
                await write_message(
                    writer, TwoPartMessage(MsgType.CONTROL, {"op": "stop"})
                )
                await context.killed()
                await write_message(
                    writer, TwoPartMessage(MsgType.CONTROL, {"op": "kill"})
                )

        control_task = asyncio.ensure_future(_forward_control())
        done = [False]

        def _teardown() -> None:
            if done[0]:
                return
            done[0] = True
            control_task.cancel()
            writer.close()

        async def _gen() -> AsyncIterator[dict]:
            try:
                while True:
                    try:
                        msg = await read_message(reader)
                    except (asyncio.IncompleteReadError, ConnectionError) as e:
                        raise ConnectionError("response stream dropped") from e
                    if msg.msg_type == MsgType.FRAME:
                        yield json.loads(msg.payload)
                    elif msg.msg_type == MsgType.COMPLETE:
                        return
                    elif msg.msg_type == MsgType.ERROR:
                        # Surface as an in-band error frame (Annotated shape)
                        # so Client.generate_to raises EngineError uniformly.
                        yield {
                            "event": "error",
                            "comment": [msg.header.get("message", "remote error")],
                        }
                        return
            finally:
                _teardown()
                with contextlib.suppress(asyncio.CancelledError):
                    await control_task
                with contextlib.suppress(Exception):
                    await writer.wait_closed()

        gen = _gen()
        # A never-iterated generator's finally never runs; closing the
        # socket on GC kills the request server-side so the handler can't
        # pin the inflight counter (the inproc plane's weakref guard,
        # ``inproc.py`` _finish).
        weakref.finalize(gen, _teardown)
        return gen

    async def scrape_stats(self, instance: InstanceInfo) -> dict:
        if instance.transport != "tcp" or not instance.transport_address:
            raise ConnectionError(
                f"instance {instance.instance_id} has no tcp address"
            )
        host, _, port = instance.transport_address.rpartition(":")
        try:
            reader, writer = await asyncio.open_connection(host, int(port))
        except OSError as e:
            raise ConnectionError(f"stats connect failed: {e}") from e
        try:
            await write_message(
                writer,
                TwoPartMessage(MsgType.STATS, {"instance_id": instance.instance_id}),
            )
            msg = await read_message(reader)
            if msg.msg_type == MsgType.ERROR:
                raise ConnectionError(msg.header.get("message", "stats error"))
            return msg.header
        except asyncio.IncompleteReadError as e:
            raise ConnectionError("stats stream dropped") from e
        finally:
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
