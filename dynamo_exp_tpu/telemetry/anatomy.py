"""Per-request latency + cost anatomy (docs/observability.md "Request
anatomy").

One artifact answers *"why was this request slow"*: the request's wall
time decomposed into named components — admission queue wait, prefill
compute, decode compute, host gap, compile stall, KV transfer, swap /
prefetch stall, preemption requeue, failover recovery — plus the cost
it consumed (chip-seconds, KV-page-seconds). Everything here is
assembled from signals the stack already emits:

- **offline, from spans** (:func:`anatomy_from_spans`): the trace's
  span tree is swept into non-overlapping intervals (a preemption span
  claims its instants away from the decode span it interrupts), then
  the analytic carve-outs the dispatch profiler attributes (host gap,
  compile stall) and the scheduler's per-sequence stall accounting
  (``swap_stall_s``) are split out of the compute components. The
  component sum equals the root span's duration by construction — the
  ``llmctl trace <id> --why`` invariant the calibration harness checks
  against the edge-measured latency.
- **offline, from a flight dump** (:func:`anatomy_from_flight`): the
  ring's ``admit`` / ``first_token`` / ``preempt`` / ``stall_start`` /
  ``stall_end`` / ``finish`` events replay into the same shape, so a
  wedged engine's dump still explains its victims (``llmctl flight
  --why``).
- **live, in the engine** (:func:`anatomy_from_timing`): the loop feeds
  per-sequence accumulators it already stamps (zero added host syncs —
  the sync-spy suite covers the tap sites) and keeps the worst-N
  exemplars in an :class:`AnatomyRing` (``llmctl slow`` /
  ``metrics()["anatomy_slow"]``).

Determinism: every function here is pure arithmetic over its inputs —
no wall-clock reads, no ids — so same-seed runs decompose identically
modulo the wall times the recorder stamped (the dynlint determinism
zone enforces this statically).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from .slo import PRIORITY_NAMES

# The closed component set: the prometheus label space
# (``dynamo_request_seconds{component}``), the metrics() mirror, the
# bench per-line summary, and the SimReport rollup all key on these
# names, in this display order. ``other`` is edge/routing overhead the
# engine never sees (preprocess, HTTP, scheduling gaps) — it exists so
# the component sum matches the edge-measured latency exactly.
COMPONENTS = (
    "queue_wait",
    "prefill_compute",
    "decode_compute",
    "host_gap",
    "compile_stall",
    "kv_transfer",
    "swap_stall",
    "preemption",
    "recovery",
    "other",
)

# Span stage -> (component, claim priority). Higher priority claims win
# an instant when spans overlap: a preemption or KV-transfer span
# happening *inside* the decode window must take those instants away
# from decode, not double-count them.
_STAGE_CLAIMS = {
    "kv_transfer_send": ("kv_transfer", 5),
    "kv_transfer_recv": ("kv_transfer", 5),
    "preemption": ("preemption", 4),
    "recovery": ("recovery", 4),
    "queue_wait": ("queue_wait", 3),
    "prefill": ("prefill_compute", 2),
    "decode": ("decode_compute", 2),
    # The decode side's local view of a remote prefill hop: lowest
    # priority, so the remote instance's own prefill / transfer spans
    # refine it wherever they overlap.
    "remote_prefill": ("prefill_compute", 1),
}


@dataclass
class RequestAnatomy:
    """One request's full latency/cost decomposition."""

    request_id: str = ""
    trace_id: str = ""
    # Every COMPONENTS key present, seconds, rounded to 6dp.
    components: dict[str, float] = field(default_factory=dict)
    # The latency the decomposition explains: root-span (edge) duration
    # offline, submit->finish for engine-side assembly.
    edge_latency_s: float = 0.0
    ttft_s: float | None = None
    itl_s: float | None = None
    # Cost: wall time the request held device compute (slot-resident,
    # not swapped/preempted) and its page-residency integral.
    chip_seconds: float = 0.0
    kv_page_seconds: float = 0.0
    prompt_tokens: int = 0
    generated_tokens: int = 0
    priority: int = 1
    instances: tuple = ()

    @property
    def total_s(self) -> float:
        return sum(self.components.values())

    @property
    def dominant(self) -> str:
        """The component that cost the most time (ties break in
        COMPONENTS display order, deterministically)."""
        if not self.components:
            return "other"
        return max(
            COMPONENTS,
            key=lambda c: (self.components.get(c, 0.0), -COMPONENTS.index(c)),
        )

    @classmethod
    def from_dict(cls, d: dict) -> "RequestAnatomy":
        """Inverse of :meth:`to_dict` (tolerant: unknown keys ignored,
        missing keys default) — `llmctl slow` rebuilds exemplars from
        scraped ``metrics()["anatomy_slow"]`` entries with this."""
        a = cls(
            request_id=str(d.get("request_id", "")),
            trace_id=str(d.get("trace_id", "")),
            components={
                k: float(v)
                for k, v in (d.get("components") or {}).items()
                if k in COMPONENTS
            },
            edge_latency_s=float(d.get("edge_latency_s", 0.0)),
            chip_seconds=float(d.get("chip_seconds", 0.0)),
            kv_page_seconds=float(d.get("kv_page_seconds", 0.0)),
            prompt_tokens=int(d.get("prompt_tokens", 0)),
            generated_tokens=int(d.get("generated_tokens", 0)),
            priority=int(d.get("priority", 1)),
            instances=tuple(d.get("instances") or ()),
        )
        if d.get("ttft_s") is not None:
            a.ttft_s = float(d["ttft_s"])
        if d.get("itl_s") is not None:
            a.itl_s = float(d["itl_s"])
        return a

    def to_dict(self) -> dict:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "components": {k: round(v, 6) for k, v in self.components.items()},
            "edge_latency_s": round(self.edge_latency_s, 6),
            "ttft_s": round(self.ttft_s, 6) if self.ttft_s is not None else None,
            "itl_s": round(self.itl_s, 6) if self.itl_s is not None else None,
            "chip_seconds": round(self.chip_seconds, 6),
            "kv_page_seconds": round(self.kv_page_seconds, 6),
            "prompt_tokens": self.prompt_tokens,
            "generated_tokens": self.generated_tokens,
            "priority": self.priority,
            "dominant": self.dominant,
            "instances": list(self.instances),
        }


def _empty_components() -> dict[str, float]:
    return dict.fromkeys(COMPONENTS, 0.0)


def _sweep_claims(
    t0: float, t1: float, claims: list[tuple[float, float, str, int]]
) -> dict[str, float]:
    """Assign every instant of [t0, t1] to the highest-priority claim
    covering it (seconds per component; unclaimed time is dropped —
    the caller books it as ``other``). Pure and deterministic: ties on
    priority break by claim insertion order."""
    comp = _empty_components()
    points = sorted(
        {t0, t1}
        | {max(min(s, t1), t0) for s, _e, _c, _p in claims}
        | {max(min(e, t1), t0) for _s, e, _c, _p in claims}
    )
    for a, b in zip(points, points[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        best = None
        for s, e, c, p in claims:
            if s <= mid < e and (best is None or p > best[1]):
                best = (c, p)
        if best is not None:
            comp[best[0]] += b - a
    return comp


def anatomy_from_spans(spans) -> RequestAnatomy | None:
    """Decompose one trace's spans (``telemetry.timeline.find_trace``
    output) into a :class:`RequestAnatomy`.

    The root interval is the ``http_request`` span when present (edge
    latency), else the trace's overall extent. Component sum equals the
    root duration exactly: the sweep partitions it, carve-outs
    (host gap / compile stall / swap stall) move time *between*
    components, and the unclaimed remainder books as ``other``."""
    if not spans:
        return None
    root = next((s for s in spans if s.stage == "http_request"), None)
    t0 = root.start if root is not None else min(s.start for s in spans)
    t1 = root.end if root is not None else max(s.end for s in spans)
    edge = max(t1 - t0, 0.0)

    claims: list[tuple[float, float, str, int]] = []
    prefill_spans, decode_spans = [], []
    for s in spans:
        claim = _STAGE_CLAIMS.get(s.stage)
        if claim is not None:
            claims.append((s.start, s.end, claim[0], claim[1]))
        if s.stage == "prefill":
            prefill_spans.append(s)
        elif s.stage == "decode":
            decode_spans.append(s)

    comp = _sweep_claims(t0, t1, claims)
    comp["other"] = max(edge - sum(comp.values()), 0.0)

    # Carve-outs: analytic splits *within* a swept component, so the
    # total is preserved by construction.
    compile_s = sum(
        float(s.attrs.get("compile_s", 0.0) or 0.0) for s in prefill_spans
    )
    compile_s = min(compile_s, comp["prefill_compute"])
    comp["prefill_compute"] -= compile_s
    comp["compile_stall"] += compile_s

    swap_s = sum(
        float(s.attrs.get("swap_stall_s", 0.0) or 0.0) for s in decode_spans
    )
    swap_s = min(swap_s, comp["decode_compute"])
    comp["decode_compute"] -= swap_s
    comp["swap_stall"] += swap_s

    # Host gap: the dispatch profiler's median per-dispatch gap vs
    # in-flight split, applied as a fraction of the remaining decode
    # compute (the two buckets partition decode wall time by the PR-8
    # profiling contract).
    gap_frac = 0.0
    for s in decode_spans:
        d = float(s.attrs.get("dispatch_p50_s", 0.0) or 0.0)
        g = float(s.attrs.get("host_gap_p50_s", 0.0) or 0.0)
        if d + g > 0:
            gap_frac = g / (d + g)
            break
    gap_s = comp["decode_compute"] * gap_frac
    comp["decode_compute"] -= gap_s
    comp["host_gap"] += gap_s

    a = RequestAnatomy(
        components={k: round(v, 6) for k, v in comp.items()},
        edge_latency_s=round(edge, 6),
    )
    if root is not None:
        a.request_id = str(root.attrs.get("request_id", ""))
        for key, attr in (("ttft_s", "ttft_s"), ("itl_s", "itl_s")):
            v = root.attrs.get(attr)
            if v is not None:
                setattr(a, key, float(v))
    a.trace_id = spans[0].trace_id
    if a.ttft_s is None and prefill_spans and root is not None:
        a.ttft_s = round(
            max(max(s.end for s in prefill_spans) - t0, 0.0), 6
        )
    for s in prefill_spans:
        a.prompt_tokens = max(a.prompt_tokens, int(s.attrs.get("prompt_tokens", 0) or 0))
    pages = 0
    for s in decode_spans:
        a.generated_tokens += int(s.attrs.get("generated_tokens", 0) or 0)
        pages = max(pages, int(s.attrs.get("pages", 0) or 0))
        if "priority" in s.attrs:
            a.priority = int(s.attrs["priority"])
    a.instances = tuple(
        sorted({str(s.attrs["instance"]) for s in spans if s.attrs.get("instance")})
    )
    compute = (
        comp["prefill_compute"] + comp["compile_stall"]
        + comp["decode_compute"] + comp["host_gap"]
    )
    a.chip_seconds = round(compute, 6)
    a.kv_page_seconds = round(pages * compute, 6)
    return a


def anatomy_from_timing(
    request_id: str,
    *,
    queue_s: float,
    prefill_s: float,
    decode_s: float,
    compile_s: float,
    swap_s: float,
    preempt_s: float,
    gap_frac: float,
    edge_latency_s: float,
    ttft_s: float | None = None,
    itl_s: float | None = None,
    prompt_tokens: int = 0,
    generated_tokens: int = 0,
    priority: int = 1,
    page_seconds: float = 0.0,
) -> RequestAnatomy:
    """Engine-side assembly from the loop's per-sequence accumulators
    (pure arithmetic; the caller stamps all times). ``gap_frac`` is the
    profiler's host-gap share of a decode dispatch interval;
    ``compile_s`` / ``swap_s`` are clamped into their parent
    components so the sum stays exact."""
    comp = _empty_components()
    comp["queue_wait"] = max(queue_s, 0.0)
    compile_c = min(max(compile_s, 0.0), max(prefill_s, 0.0))
    comp["compile_stall"] = compile_c
    comp["prefill_compute"] = max(prefill_s, 0.0) - compile_c
    swap_c = min(max(swap_s, 0.0), max(decode_s, 0.0))
    comp["swap_stall"] = swap_c
    decode_c = max(decode_s, 0.0) - swap_c
    gap = decode_c * min(max(gap_frac, 0.0), 1.0)
    comp["host_gap"] = gap
    comp["decode_compute"] = decode_c - gap
    comp["preemption"] = max(preempt_s, 0.0)
    comp["other"] = max(edge_latency_s - sum(comp.values()), 0.0)
    compute = (
        comp["prefill_compute"] + comp["compile_stall"]
        + comp["decode_compute"] + comp["host_gap"]
    )
    return RequestAnatomy(
        request_id=request_id,
        components={k: round(v, 6) for k, v in comp.items()},
        edge_latency_s=round(max(edge_latency_s, 0.0), 6),
        ttft_s=ttft_s,
        itl_s=itl_s,
        chip_seconds=round(compute, 6),
        kv_page_seconds=round(page_seconds, 6),
        prompt_tokens=prompt_tokens,
        generated_tokens=generated_tokens,
        priority=priority,
    )


def anatomy_from_flight(block: dict, request_id: str | None = None) -> list[RequestAnatomy]:
    """Reconstruct per-request anatomies from one flight-dump block
    (``telemetry.flight.load_dumps`` output) — the engine's ring alone,
    no span file needed. The ``admit`` / ``first_token`` / ``preempt``
    / ``stall_start`` / ``stall_end`` / ``finish`` events replay
    through a per-request state machine; requests whose admit or finish
    fell off the ring are skipped (a bounded ring can only explain what
    it still holds)."""
    events = sorted(block.get("events") or [], key=lambda e: (e.get("t", 0.0), e.get("seq", 0)))
    state: dict[str, dict] = {}
    out: list[RequestAnatomy] = []
    for ev in events:
        req = ev.get("req")
        if req is None or (request_id is not None and req != request_id):
            continue
        kind = ev.get("kind")
        t = float(ev.get("t", 0.0))
        st = state.get(req)
        if kind == "admit":
            if st is None:
                st = state[req] = {
                    "t_admit": t, "queue": 0.0, "prefill": 0.0,
                    "decode": 0.0, "stall": 0.0, "preempt": 0.0,
                    "t_mark": t, "phase": "prefill", "stall_since": 0.0,
                    "prompt": int(ev.get("prompt", 0) or 0),
                    "cached": int(ev.get("cached", 0) or 0),
                    "priority": int(ev.get("priority", 1) or 1),
                }
            else:  # re-admission after preemption
                st["preempt"] += max(t - st["t_mark"], 0.0)
                st["t_mark"] = t
                st["phase"] = "prefill"
        elif st is None:
            continue
        elif kind == "first_token":
            st["prefill"] += max(t - st["t_mark"], 0.0)
            st["t_mark"] = t
            st["phase"] = "decode"
        elif kind == "preempt":
            st[st["phase"]] += max(t - st["t_mark"], 0.0)
            st["t_mark"] = t
            st["phase"] = "preempt"
        elif kind == "stall_start":
            st["stall_since"] = t
        elif kind == "stall_end":
            if st["stall_since"]:
                st["stall"] += max(t - st["stall_since"], 0.0)
                st["stall_since"] = 0.0
        elif kind == "finish":
            st[st["phase"]] += max(t - st["t_mark"], 0.0)
            edge = max(t - st["t_admit"], 0.0)
            a = anatomy_from_timing(
                str(req),
                queue_s=0.0,  # submission isn't a ring event
                prefill_s=st["prefill"],
                decode_s=st["decode"],
                compile_s=0.0,
                swap_s=min(st["stall"], st["decode"]),
                preempt_s=st["preempt"],
                gap_frac=0.0,
                edge_latency_s=edge,
                prompt_tokens=st["prompt"],
                generated_tokens=int(ev.get("generated", 0) or 0),
                priority=int(ev.get("priority", st["priority"]) or 1),
                page_seconds=float(ev.get("pages", 0) or 0) * edge,
            )
            out.append(a)
            state.pop(req, None)
    return out


class AnatomyRing:
    """Bounded worst-N exemplar ring: the slowest requests (by edge
    latency) retain their full anatomy, so the p99 offenders are
    explainable after the fact without a span file. Thread-safe —
    ``offer`` runs on the engine loop while ``metrics()`` snapshots
    from serving threads."""

    def __init__(self, capacity: int = 16):
        self.capacity = max(capacity, 1)
        self._lock = threading.Lock()
        self._worst: list[RequestAnatomy] = []

    def offer(self, anatomy: RequestAnatomy) -> None:
        with self._lock:
            self._worst.append(anatomy)
            self._worst.sort(key=lambda a: -a.edge_latency_s)
            del self._worst[self.capacity:]

    def snapshot(self) -> list[dict]:
        """Worst-first compact dicts (the ``anatomy_slow`` mirror)."""
        with self._lock:
            return [a.to_dict() for a in self._worst]


# ---------------------------------------------------------------- rendering
def _fmt_priority(p) -> str:
    return PRIORITY_NAMES.get(p, str(p))


def render_anatomy(a: RequestAnatomy, width: int = 30) -> str:
    """The ``--why`` waterfall: every component with its share bar, the
    dominant one named up top, cost footer below."""
    total = max(a.edge_latency_s, a.total_s, 1e-9)
    head = (
        f"request {a.request_id or a.trace_id or '?'} — "
        f"{a.edge_latency_s * 1e3:.1f}ms edge latency, dominant: "
        f"{a.dominant} "
        f"({a.components.get(a.dominant, 0.0) / total:.0%})"
    )
    if len(a.instances) > 1:
        head += f" [across {len(a.instances)} instances]"
    lines = [head]
    for c in COMPONENTS:
        v = a.components.get(c, 0.0)
        frac = v / total
        bar = "#" * max(int(round(frac * width)), 1 if v > 0 else 0)
        lines.append(
            f"  {c:<16} {v * 1e3:9.1f}ms {frac:5.0%} |{bar:<{width}}|"
        )
    foot = (
        f"  chip-seconds {a.chip_seconds:.3f}, kv-page-seconds "
        f"{a.kv_page_seconds:.3f}, prompt {a.prompt_tokens}, generated "
        f"{a.generated_tokens}, priority {_fmt_priority(a.priority)}"
    )
    if a.ttft_s is not None:
        foot += f", ttft {a.ttft_s * 1e3:.1f}ms"
    if a.itl_s is not None:
        foot += f", itl {a.itl_s * 1e3:.2f}ms"
    lines.append(foot)
    return "\n".join(lines)


def render_slow(anatomies: list[RequestAnatomy], n: int = 10, by: str = "edge") -> str:
    """The ``llmctl slow`` listing: worst-N offenders by edge latency,
    TTFT, or ITL, one line each with the dominant component named."""
    keys = {
        "edge": lambda a: a.edge_latency_s,
        "ttft": lambda a: a.ttft_s or 0.0,
        "itl": lambda a: a.itl_s or 0.0,
    }
    key = keys.get(by, keys["edge"])
    rows = sorted(anatomies, key=lambda a: -key(a))[:n]
    if not rows:
        return "no requests with anatomy"
    lines = [
        f"slowest {len(rows)} request(s) by {by}:",
        f"  {'request':<28} {'edge':>9} {'ttft':>9} {'itl':>9}  dominant",
    ]
    for a in rows:
        ttft = f"{a.ttft_s * 1e3:.1f}ms" if a.ttft_s is not None else "-"
        itl = f"{a.itl_s * 1e3:.2f}ms" if a.itl_s is not None else "-"
        lines.append(
            f"  {(a.request_id or a.trace_id or '?')[:28]:<28} "
            f"{a.edge_latency_s * 1e3:8.1f}ms {ttft:>9} {itl:>9}  "
            f"{a.dominant}"
        )
    return "\n".join(lines)
