"""Deploy tier: artifact build, artifact/deployment store, K8s rendering.

Capability parity with the reference's deployment stack
(``/root/reference/deploy/dynamo/``): ``dynamo build`` Bento-style
artifact packaging (``cli/bentos.py``), the api-store artifact registry
(``api-store/ai_dynamo_store/api/``), and the Go K8s operator's
manifest generation (``operator/``) — redesigned for TPU clusters:
artifacts are plain content-addressed tarballs of the SDK graph, and
rendering targets GKE TPU node pools (``google.com/tpu`` resources +
TPU node selectors) with the self-hosted coordinator as the control
plane instead of etcd+NATS.
"""

from .artifact import ArtifactManifest, build_artifact, read_manifest
from .k8s import render_graph_manifests, to_yaml

__all__ = [
    "ArtifactManifest",
    "build_artifact",
    "read_manifest",
    "render_graph_manifests",
    "to_yaml",
]
