"""OpenAI-compatible HTTP ingress."""

from .admission import (
    AdmissionController,
    RequestShedError,
    ServiceOverloadedError,
)
from .metrics import ServiceMetrics
from .service import HttpService, ModelManager, build_pipeline_engine

__all__ = [
    "AdmissionController",
    "HttpService",
    "ModelManager",
    "RequestShedError",
    "ServiceMetrics",
    "ServiceOverloadedError",
    "build_pipeline_engine",
]
