"""Deterministic fault injection for the distributed fabric.

Wraps any :class:`RequestPlane` / :class:`Discovery` / :class:`WorkQueue`
with a seeded, scripted fault schedule so failure scenarios — worker
crash at stream start or mid-stream, network partition, discovery watch
flaps, work-queue outages, injected latency — are reproducible
bit-for-bit across runs (``tests/test_fault_tolerance.py``, the
``chaos`` pytest marker, ``make chaos``).

Two fault sources compose:

- **scripted faults** (:meth:`ChaosSchedule.add` and its shorthands):
  consumed in insertion order whenever a matching op fires, each a fixed
  number of times. Deterministic by construction.
- **partitions** (:meth:`ChaosSchedule.partition` / :meth:`heal`): a set
  of instance ids that are unreachable until healed — the "machine
  dropped off the network" primitive.

The only randomness is delay jitter, drawn from ``random.Random(seed)``,
so a given (seed, script, workload) triple always injects the same
faults at the same points. Every injected fault is appended to
:attr:`ChaosSchedule.injected` for assertions and cross-run comparison.
"""

from __future__ import annotations

import asyncio
import contextlib
import random
from dataclasses import dataclass
from typing import AsyncIterator

from ..engine import AsyncEngineContext
from .base import (
    Discovery,
    Handler,
    InstanceInfo,
    Lease,
    RequestPlane,
    ServedEndpoint,
    StatsHandler,
    WorkQueue,
)


@dataclass
class Fault:
    """One scripted fault.

    ``op`` selects the interception point: ``request`` / ``stats`` on the
    request plane, ``watch`` / ``list`` on discovery, ``push`` / ``pull``
    / ``size`` on a work queue.

    ``kind``: ``error`` raises :class:`ConnectionError` (for ``request``,
    :attr:`after_frames` refines *when*: ``None`` fails the dispatch
    itself, ``N >= 0`` starts the stream and kills it after N frames —
    the worker-crash-mid-stream shape; :attr:`after_tokens` is the same
    cut expressed in **tokens**: the stream dies once N tokens have been
    delivered, counting ``len(frame.data.token_ids)`` per frame — the
    kill-at-token-K primitive resumable-stream tests script); ``delay``
    sleeps ``delay_s`` (plus seeded jitter) and then proceeds normally.

    ``times``: how many matching calls consume this fault (-1 = every
    matching call until the schedule is cleared).
    """

    op: str
    kind: str = "error"
    instance_id: int | None = None
    after_frames: int | None = None
    after_tokens: int | None = None
    delay_s: float = 0.0
    times: int = 1
    message: str = "chaos: injected fault"


class ChaosSchedule:
    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: list[Fault] = []
        self.partitioned: set[int] = set()
        # Log of every fault that actually fired, for determinism checks.
        self.injected: list[str] = []

    # ------------------------------------------------------------ script
    def add(self, fault: Fault) -> "ChaosSchedule":
        self.faults.append(fault)
        return self

    def fail_requests(
        self,
        instance_id: int | None = None,
        times: int = 1,
        after_frames: int | None = None,
    ) -> "ChaosSchedule":
        return self.add(
            Fault(
                "request",
                instance_id=instance_id,
                times=times,
                after_frames=after_frames,
                message="chaos: request failed"
                if after_frames is None
                else "chaos: stream dropped",
            )
        )

    def crash_at_token(
        self, k: int, instance_id: int | None = None, times: int = 1
    ) -> "ChaosSchedule":
        """Kill the response stream once exactly ``k`` tokens have been
        delivered (frames without ``token_ids`` pass through untouched) —
        the decode-worker-dies-mid-generation shape the resumable-stream
        suite replays at several k."""
        return self.add(
            Fault(
                "request",
                instance_id=instance_id,
                times=times,
                after_tokens=k,
                message=f"chaos: decode worker crashed at token {k}",
            )
        )

    def drain_timeout(
        self, instance_id: int | None = None, after_tokens: int = 0, times: int = 1
    ) -> "ChaosSchedule":
        """A graceful drain whose grace period expires mid-stream: the
        instance cuts the connection after ``after_tokens`` tokens
        instead of finishing the request. Distinguished from a crash by
        its message, so recovery telemetry labels it ``drain``."""
        return self.add(
            Fault(
                "request",
                instance_id=instance_id,
                times=times,
                after_tokens=after_tokens,
                message="chaos: drain grace period exceeded mid-stream",
            )
        )

    def reclaim_at(
        self,
        after_tokens: int,
        instance_id: int | None = None,
        grace_s: float = 1.0,
        times: int = 1,
    ) -> "ChaosSchedule":
        """A spot reclamation landing mid-stream: the platform takes the
        instance back after ``after_tokens`` tokens, cutting the
        connection with a reclaim-tagged message (``grace_s`` rides the
        message for the log) so recovery telemetry labels the failover
        ``reclaim`` and the journal continuation resumes on a survivor
        (docs/fault_tolerance.md "Spot reclamation & live migration")."""
        return self.add(
            Fault(
                "request",
                instance_id=instance_id,
                times=times,
                after_tokens=after_tokens,
                message=(
                    f"chaos: instance reclaimed mid-stream "
                    f"(grace {grace_s:g}s)"
                ),
            )
        )

    def fail_watch(self, times: int = 1) -> "ChaosSchedule":
        return self.add(Fault("watch", times=times, message="chaos: watch broke"))

    def fail_queue(self, op: str, times: int = 1) -> "ChaosSchedule":
        assert op in ("push", "pull", "size")
        return self.add(Fault(op, times=times, message=f"chaos: queue {op} down"))

    def delay_requests(
        self, delay_s: float, instance_id: int | None = None, times: int = 1
    ) -> "ChaosSchedule":
        return self.add(
            Fault(
                "request",
                kind="delay",
                instance_id=instance_id,
                delay_s=delay_s,
                times=times,
            )
        )

    def partition(self, *instance_ids: int) -> "ChaosSchedule":
        self.partitioned.update(instance_ids)
        return self

    def heal(self, *instance_ids: int) -> "ChaosSchedule":
        if instance_ids:
            self.partitioned.difference_update(instance_ids)
        else:
            self.partitioned.clear()
        return self

    def clear(self) -> "ChaosSchedule":
        self.faults.clear()
        self.partitioned.clear()
        return self

    # ----------------------------------------------------------- consume
    def take(self, op: str, instance_id: int | None = None) -> Fault | None:
        for f in self.faults:
            if f.op != op or f.times == 0:
                continue
            if (
                f.instance_id is not None
                and instance_id is not None
                and f.instance_id != instance_id
            ):
                continue
            if f.times > 0:
                f.times -= 1
            self.injected.append(f"{op}:{instance_id}:{f.kind}")
            return f
        return None

    async def apply_delay(self, fault: Fault) -> None:
        jitter = self.rng.random() * fault.delay_s * 0.1
        await asyncio.sleep(fault.delay_s + jitter)


class StorageChaos:
    """Seeded storage-fault schedule for the G3 persistent KV tier
    (docs/fault_tolerance.md "Durable KV & corruption containment").

    Same consume-in-order contract as :class:`ChaosSchedule`, over the
    store's two interception points — ``store_write`` (demotion /
    shutdown drain) and ``store_read`` (promotion / re-attach fetch) —
    with storage-flavoured kinds:

    - ``enospc``: the write raises ``OSError(ENOSPC)`` → the store
      counts it and flips :attr:`~dynamo_exp_tpu.kv.persistent.PersistentKvStore.degraded`
      (engine falls back to G2-only, never a stall).
    - ``torn``: the page file lands as a truncated prefix of the real
      bytes — the power-cut-mid-write shape ``boot_scan`` and the fetch
      checksum must both reject.
    - ``bitflip``: one payload byte of the *read* is flipped at a
      position drawn from the seeded rng — fetch must checksum-fail,
      quarantine, and miss; never serve the garbage.
    - ``delay``: the read sleeps ``delay_s`` first — a slow SSD must
      slow restores, never wedge the engine loop.

    The fifth family member — store-dir missing — needs no schedule: it
    is exercised by constructing the store over an uncreatable path.
    Every fired fault lands in :attr:`injected` for assertions.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: list[Fault] = []
        self.injected: list[str] = []

    # ------------------------------------------------------------ script
    def add(self, fault: Fault) -> "StorageChaos":
        assert fault.op in ("store_write", "store_read")
        self.faults.append(fault)
        return self

    def enospc(self, times: int = 1) -> "StorageChaos":
        return self.add(
            Fault(
                "store_write",
                kind="enospc",
                times=times,
                message="chaos: no space left on device",
            )
        )

    def torn_write(self, times: int = 1) -> "StorageChaos":
        return self.add(
            Fault(
                "store_write",
                kind="torn",
                times=times,
                message="chaos: torn page write",
            )
        )

    def bitflip_read(self, times: int = 1) -> "StorageChaos":
        return self.add(
            Fault(
                "store_read",
                kind="bitflip",
                times=times,
                message="chaos: bit flipped in stored page",
            )
        )

    def delay_read(self, delay_s: float, times: int = 1) -> "StorageChaos":
        return self.add(
            Fault(
                "store_read",
                kind="delay",
                delay_s=delay_s,
                times=times,
                message="chaos: slow store read",
            )
        )

    def clear(self) -> "StorageChaos":
        self.faults.clear()
        return self

    # ----------------------------------------------------------- consume
    def take(self, op: str) -> Fault | None:
        for f in self.faults:
            if f.op != op or f.times == 0:
                continue
            if f.times > 0:
                f.times -= 1
            self.injected.append(f"{op}:{f.kind}")
            return f
        return None


@dataclass
class BurstRequest:
    """One request of a seeded overload burst (see :func:`overload_burst`)."""

    index: int
    priority: str  # "low" | "normal" | "high"
    prompt: list[int]
    max_tokens: int
    delay_s: float  # submission offset within the burst
    seed: int  # sampling seed (pinned, so replays are token-identical)


def overload_burst(
    seed: int,
    n: int = 12,
    priorities: tuple[str, ...] = ("low", "normal", "high"),
    isl_range: tuple[int, int] = (4, 12),
    osl_range: tuple[int, int] = (6, 16),
    vocab_range: tuple[int, int] = (3, 200),
    spread_s: float = 0.0,
) -> list[BurstRequest]:
    """A deterministic N-request burst with mixed priorities — the
    overload-protection scenario (``tests/test_overload.py``, ``make
    chaos``): fired against a deliberately tiny KV pool, it must drive
    edge shedding (429/503) and engine KV-pressure preemption without
    ever hanging a request.

    Everything — prompts, lengths, priorities, per-request sampling
    seeds, submission jitter — derives from ``random.Random(seed)``, so
    a given seed always produces the same burst and cross-run
    determinism assertions hold bit-for-bit.
    """
    rng = random.Random(seed)
    burst = []
    for i in range(n):
        isl = rng.randint(*isl_range)
        burst.append(
            BurstRequest(
                index=i,
                priority=priorities[rng.randrange(len(priorities))],
                prompt=[rng.randint(*vocab_range) for _ in range(isl)],
                max_tokens=rng.randint(*osl_range),
                delay_s=rng.random() * spread_s,
                seed=rng.getrandbits(31),
            )
        )
    return burst


class ChaosRequestPlane(RequestPlane):
    """RequestPlane decorator injecting scheduled faults client-side."""

    def __init__(self, inner: RequestPlane, schedule: ChaosSchedule):
        self.inner = inner
        self.schedule = schedule

    async def serve(
        self,
        info: InstanceInfo,
        handler: Handler,
        stats_handler: StatsHandler | None = None,
    ) -> ServedEndpoint:
        return await self.inner.serve(info, handler, stats_handler)

    async def request_stream(
        self,
        instance: InstanceInfo,
        request: dict,
        context: AsyncEngineContext,
    ) -> AsyncIterator[dict]:
        iid = instance.instance_id
        if iid in self.schedule.partitioned:
            self.schedule.injected.append(f"request:{iid}:partition")
            raise ConnectionError(f"chaos: instance {iid} partitioned")
        fault = self.schedule.take("request", iid)
        if fault is not None:
            if fault.kind == "delay":
                await self.schedule.apply_delay(fault)
            elif fault.after_tokens is not None:
                inner = await self.inner.request_stream(
                    instance, request, context
                )
                return _drop_after_tokens(
                    inner, fault.after_tokens, fault.message
                )
            elif fault.after_frames is None:
                raise ConnectionError(fault.message)
            else:
                inner = await self.inner.request_stream(
                    instance, request, context
                )
                return _drop_after(inner, fault.after_frames, fault.message)
        return await self.inner.request_stream(instance, request, context)

    async def scrape_stats(self, instance: InstanceInfo) -> dict:
        iid = instance.instance_id
        if iid in self.schedule.partitioned:
            raise ConnectionError(f"chaos: instance {iid} partitioned")
        fault = self.schedule.take("stats", iid)
        if fault is not None and fault.kind == "error":
            raise ConnectionError(fault.message)
        return await self.inner.scrape_stats(instance)

    async def close(self) -> None:
        await self.inner.close()


async def _drop_after(
    frames: AsyncIterator[dict], n: int, message: str
) -> AsyncIterator[dict]:
    """Yield ``n`` frames, then die like a crashed worker connection."""
    produced = 0
    async for frame in frames:
        if produced >= n:
            closer = getattr(frames, "aclose", None)
            if closer is not None:
                with contextlib.suppress(Exception):
                    await closer()
            raise ConnectionError(message)
        yield frame
        produced += 1
    if produced < n:
        return  # stream ended before the scheduled crash point
    raise ConnectionError(message)


async def _drop_after_tokens(
    frames: AsyncIterator[dict], k: int, message: str
) -> AsyncIterator[dict]:
    """Yield frames until ``k`` tokens have been delivered, then die like
    a crashed worker connection — immediately after the frame that
    reaches the count (so a trailing finish/usage frame is lost with the
    connection, exactly like a real crash). ``k=0`` kills before the
    first token-bearing frame. Token counting inspects the engine-frame
    shape (``data.token_ids``). A stream that ends before K tokens never
    reaches its scheduled crash point (mirrors ``_drop_after``)."""
    delivered = 0
    async for frame in frames:
        data = frame.get("data") if isinstance(frame, dict) else None
        n_toks = (
            len(data.get("token_ids") or []) if isinstance(data, dict) else 0
        )
        crash_before = n_toks > 0 and delivered >= k  # only when k == 0
        if not crash_before:
            yield frame
            delivered += n_toks
        if crash_before or delivered >= k:
            closer = getattr(frames, "aclose", None)
            if closer is not None:
                with contextlib.suppress(Exception):
                    await closer()
            raise ConnectionError(message)


class ChaosDiscovery(Discovery):
    """Discovery decorator: watch flaps and list outages on schedule.

    Registration/KV ops pass straight through — the scenarios under test
    are consumer-side (clients and routers), not publisher-side.
    """

    def __init__(self, inner: Discovery, schedule: ChaosSchedule):
        self.inner = inner
        self.schedule = schedule

    async def register_instance(
        self, info: InstanceInfo, lease: Lease | None = None
    ) -> Lease:
        return await self.inner.register_instance(info, lease)

    async def create_lease(self, ttl_s: float | None = None) -> Lease:
        return await self.inner.create_lease(ttl_s)

    async def deregister_instance(self, instance_id: int) -> None:
        await self.inner.deregister_instance(instance_id)

    async def list_instances(self, prefix: str) -> list[InstanceInfo]:
        fault = self.schedule.take("list")
        if fault is not None and fault.kind == "error":
            raise ConnectionError(fault.message)
        return await self.inner.list_instances(prefix)

    async def watch_instances(
        self, prefix: str
    ) -> AsyncIterator[list[InstanceInfo]]:
        # The flap fires *after* a snapshot is delivered: the consumer saw
        # data, then the stream broke — the shape Client._watch must
        # survive by re-subscribing.
        async for snapshot in self.inner.watch_instances(prefix):
            yield snapshot
            fault = self.schedule.take("watch")
            if fault is not None and fault.kind == "error":
                raise ConnectionError(fault.message)

    async def kv_put(self, key: str, value: bytes, lease: Lease | None = None) -> None:
        await self.inner.kv_put(key, value, lease)

    async def kv_create(
        self, key: str, value: bytes, lease: Lease | None = None
    ) -> bool:
        return await self.inner.kv_create(key, value, lease)

    async def kv_get(self, key: str) -> bytes | None:
        return await self.inner.kv_get(key)

    async def kv_get_prefix(self, prefix: str) -> dict[str, bytes]:
        return await self.inner.kv_get_prefix(prefix)

    async def kv_delete(self, key: str) -> None:
        await self.inner.kv_delete(key)

    async def kv_watch_prefix(self, prefix: str) -> AsyncIterator[dict[str, bytes]]:
        async for snapshot in self.inner.kv_watch_prefix(prefix):
            yield snapshot

    # Sibling planes ride the inner fabric; queues get the chaos wrapper
    # so disagg scenarios can take the prefill queue down.
    def _new_event_plane(self):
        return self.inner.event_plane()

    def _new_work_queue(self, name: str) -> "ChaosWorkQueue":
        return ChaosWorkQueue(self.inner.work_queue(name), self.schedule)

    def _new_object_store(self):
        return self.inner.object_store()

    async def close(self) -> None:
        await self.inner.close()


class ChaosWorkQueue(WorkQueue):
    """WorkQueue decorator: outages on push/pull/size."""

    def __init__(self, inner: WorkQueue, schedule: ChaosSchedule):
        self.inner = inner
        self.schedule = schedule

    async def push(self, payload: bytes) -> None:
        fault = self.schedule.take("push")
        if fault is not None:
            if fault.kind == "delay":
                await self.schedule.apply_delay(fault)
            else:
                raise ConnectionError(fault.message)
        await self.inner.push(payload)

    async def pull(self, timeout_s: float | None = None) -> bytes | None:
        fault = self.schedule.take("pull")
        if fault is not None and fault.kind == "error":
            raise ConnectionError(fault.message)
        return await self.inner.pull(timeout_s)

    async def size(self) -> int:
        fault = self.schedule.take("size")
        if fault is not None and fault.kind == "error":
            raise ConnectionError(fault.message)
        return await self.inner.size()
