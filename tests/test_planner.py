"""Planner tests: threshold decisions (unit) and the full scale-up /
scale-down loop against a real supervisor + coordinator (e2e).

Reference capability anchors:
``examples/llm/components/planner.py:225-305`` (decision policy),
``components/planner/src/dynamo/planner/local_connector.py`` (scale
actions against the serve arbiter).
"""

import asyncio
import contextlib

import pytest

from dynamo_exp_tpu.planner import Planner, PlannerConfig, PlannerConnector
from dynamo_exp_tpu.planner.planner import (
    NEW_DECODE_WORKER_GRACE_PERIOD,
    prefill_queue_name,
)


class FakeConnector(PlannerConnector):
    def __init__(self, fail=False):
        self.calls: list[tuple[str, str]] = []
        self.fail = fail

    async def add_component(self, name):
        self.calls.append(("add", name))
        return not self.fail

    async def remove_component(self, name):
        self.calls.append(("remove", name))
        return not self.fail


def make_planner(connector, **kw) -> Planner:
    """Planner with a null runtime: unit tests inject metrics directly
    and stub discovery, so no coordinator is needed."""

    class _NullQueue:
        async def size(self):
            return 0

    class _NullDrt:
        def namespace(self, name):
            return self

        def component(self, name):
            return self

        def work_queue(self, name):
            return _NullQueue()

    cfg = PlannerConfig(adjustment_interval=0.1, **kw)
    p = Planner(_NullDrt(), cfg, connector=connector)
    return p


# ------------------------------------------------------------------- unit
async def test_decode_scale_up_on_high_kv_load():
    conn = FakeConnector()
    p = make_planner(conn)
    p.kv_load = [0.95, 0.97]
    await p.make_adjustments_with_counts([], [1])
    assert ("add", p.cfg.decode_component) in conn.calls
    assert p.decode_worker_remaining_grace_period == (
        NEW_DECODE_WORKER_GRACE_PERIOD - 1
    )


async def test_failed_decode_add_does_not_arm_grace():
    """Grace protects a NEW worker from scale-down; an add the
    connector rejected spawned nothing, so the next low-load interval
    may scale down immediately."""
    conn = FakeConnector(fail=True)
    p = make_planner(conn)
    p.kv_load = [0.95, 0.97]
    await p.make_adjustments_with_counts([], [1])
    assert ("add", p.cfg.decode_component) in conn.calls  # attempted
    assert p.decode_worker_remaining_grace_period == 0  # not armed


async def test_decode_scale_down_blocked_by_grace_period_then_allowed():
    conn = FakeConnector()
    p = make_planner(conn)
    p.decode_worker_remaining_grace_period = 2
    p.kv_load = [0.1]
    await p.make_adjustments_with_counts([], [1, 2])
    assert conn.calls == []  # grace period blocks
    p.kv_load = [0.1]
    await p.make_adjustments_with_counts([], [1, 2])
    p.kv_load = [0.1]
    await p.make_adjustments_with_counts([], [1, 2])
    assert ("remove", p.cfg.decode_component) in conn.calls


async def test_decode_scale_down_respects_min_endpoint():
    conn = FakeConnector()
    p = make_planner(conn, min_endpoint=1)
    p.kv_load = [0.0]
    await p.make_adjustments_with_counts([], [1])
    assert conn.calls == []


async def test_budget_caps_scale_up():
    conn = FakeConnector()
    p = make_planner(conn, max_tpu_budget=2, decode_engine_num_tpu=1)
    p.kv_load = [0.99]
    await p.make_adjustments_with_counts([], [1, 2])  # 2 chips in use already
    assert conn.calls == []


async def test_prefill_scale_up_needs_persistent_trend():
    conn = FakeConnector()
    p = make_planner(conn)
    # Queue deep but draining fast: trend predicts below threshold.
    p.prefill_queue_load = [20.0, 6.0]
    await p.make_adjustments_with_counts([1], [2])
    assert ("add", p.cfg.prefill_component) not in conn.calls
    # Queue deep and rising: scale up.
    p.prefill_queue_load = [6.0, 20.0]
    await p.make_adjustments_with_counts([1], [2])
    assert ("add", p.cfg.prefill_component) in conn.calls


async def test_prefill_scale_down_when_queue_idle():
    conn = FakeConnector()
    p = make_planner(conn)
    p.prefill_queue_load = [0.0, 0.0]
    p.kv_load = [0.7]
    await p.make_adjustments_with_counts([1, 2], [3])
    assert ("remove", p.cfg.prefill_component) in conn.calls


def test_prefill_queue_name_stable():
    assert prefill_queue_name("m") == "prefill-m"


async def test_planner_counts_registered_prefill_workers():
    """PrefillWorker.register() makes the fleet visible to the planner's
    discovery (the 'pull' presence endpoint)."""
    import os

    from dynamo_exp_tpu.disagg import PrefillWorker
    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models import TINY
    from dynamo_exp_tpu.parallel import single_device_mesh
    from dynamo_exp_tpu.runtime.component import DistributedRuntime
    from dynamo_exp_tpu.runtime.config import RuntimeConfig
    from dynamo_exp_tpu.runtime.transports.coordinator import CoordinatorServer

    server = CoordinatorServer()
    await server.start()
    drt = DistributedRuntime(
        config=RuntimeConfig(coordinator_endpoint=server.address)
    )
    eng = TPUEngine(
        EngineConfig(model=TINY, max_decode_slots=1, num_pages=16,
                     max_model_len=64, enable_kv_events=False),
        mesh=single_device_mesh(),
    )
    worker = PrefillWorker(
        eng,
        drt.work_queue(prefill_queue_name("m")),
        component=drt.namespace("plan").component("PrefillWorker"),
    )
    try:
        await worker.register()
        cfg = PlannerConfig(namespace="plan", decode_component="PrefillWorker")
        planner = Planner(drt, cfg, connector=FakeConnector())
        p, _d = await planner.get_workers_info()
        assert len(p) == 1
    finally:
        if worker._presence is not None:
            await worker._presence.close()
        eng.stop()
        await drt.close()
        await server.close()


# -------------------------------------------------------------------- e2e
async def test_planner_scales_supervisor_up_and_down_under_load():
    """Synthetic load → scale-up; idle → scale-down; a discovery client
    (the router's membership view) follows both transitions."""
    import os

    from dynamo_exp_tpu.runtime.component import DistributedRuntime
    from dynamo_exp_tpu.runtime.config import RuntimeConfig
    from dynamo_exp_tpu.runtime.push_router import PushRouter
    from dynamo_exp_tpu.runtime.transports.coordinator import CoordinatorServer
    from dynamo_exp_tpu.sdk.allocator import TPUAllocator
    from dynamo_exp_tpu.sdk.config import ServiceConfig
    from dynamo_exp_tpu.sdk.serve import Supervisor
    from dynamo_exp_tpu.sdk.service import discover_graph

    from .planner_graph import LoadWorker

    server = CoordinatorServer()
    await server.start()
    os.environ["DYN_RUNTIME_COORDINATOR_ENDPOINT"] = server.address
    graph = discover_graph(LoadWorker)
    sup = Supervisor(
        "tests.planner_graph:LoadWorker",
        graph,
        ServiceConfig.load(None),
        TPUAllocator(8),
        server.address,
    )
    drt = DistributedRuntime(
        config=RuntimeConfig(coordinator_endpoint=server.address)
    )
    control = await sup.serve_control(drt, "plan")
    planner = None
    tasks: list[asyncio.Task] = []
    try:
        await sup.start_initial()
        ep = drt.namespace("plan").component("LoadWorker").endpoint("generate")
        client = await ep.client()
        await client.wait_for_instances(1, timeout=30)

        cfg = PlannerConfig(
            namespace="plan",
            decode_component="LoadWorker",
            metric_pulling_interval=0.2,
            adjustment_interval=1.0,
            decode_kv_scale_up_threshold=0.7,
            decode_kv_scale_down_threshold=0.3,
            max_tpu_budget=2,
            decode_engine_num_tpu=1,
        )
        planner = Planner(drt, cfg)
        tasks.append(asyncio.ensure_future(planner.run()))

        # Synthetic load: saturate the single worker's 4 slots.
        router = PushRouter(client)

        async def drive():
            stream = await router.generate({"steps": 200})
            with contextlib.suppress(Exception):
                async for _ in stream:
                    pass

        load = [asyncio.ensure_future(drive()) for _ in range(4)]

        async def wait_for(cond, timeout):
            deadline = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < deadline:
                if cond():
                    return True
                await asyncio.sleep(0.2)
            return False

        # Scale-up observed at the supervisor AND by the discovery client.
        assert await wait_for(
            lambda: sup.counts()["LoadWorker"] >= 2, 30
        ), f"no scale-up: {planner.adjustments}"
        assert await wait_for(lambda: len(client.instance_ids()) >= 2, 30)

        # Idle: cancel the load, wait out the grace period, expect
        # scale-down back to min_endpoint and the client to see it.
        for t in load:
            t.cancel()
        await asyncio.gather(*load, return_exceptions=True)
        assert await wait_for(
            lambda: sup.counts()["LoadWorker"] == 1, 60
        ), f"no scale-down: {planner.adjustments}"
        assert await wait_for(lambda: len(client.instance_ids()) == 1, 30)
    finally:
        if planner is not None:
            planner.stop()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        await sup.stop_all()
        await control.close()
        await drt.close()
        await server.close()
        os.environ.pop("DYN_RUNTIME_COORDINATOR_ENDPOINT", None)
