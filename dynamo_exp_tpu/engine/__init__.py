from .config import EngineConfig
from .engine import TPUEngine, resolve_attn_impl
from .kv_manager import KvEvent, KvPageManager
from .offload import CopyStream, HostKvPool
from .scheduler import Scheduler, Sequence

__all__ = [
    "EngineConfig",
    "TPUEngine",
    "resolve_attn_impl",
    "KvPageManager",
    "KvEvent",
    "HostKvPool",
    "CopyStream",
    "Scheduler",
    "Sequence",
]
