"""Aggregated serving: Frontend → Processor → TpuWorker (round-robin).

Reference parity: ``/root/reference/examples/llm/graphs/agg.py``. Serve:

    python -m dynamo_exp_tpu.sdk.serve examples.llm.graphs.agg:Frontend \
        -f examples/llm/configs/agg.yaml --start-coordinator
"""

from examples.llm.components.frontend import Frontend
from examples.llm.components.processor import Processor
from examples.llm.components.worker import TpuWorker

__all__ = ["Frontend", "Processor", "TpuWorker"]
