"""OpenAIPreprocessor: OpenAI requests -> engine BackendInput, and engine
outputs -> OpenAI stream chunks.

Capability parity with ``/root/reference/lib/llm/src/preprocessor.rs``:
apply model-card defaults, render the chat template, tokenize, extract
stop conditions / sampling options / annotations; as a pipeline Operator
it also converts the backend's token/text stream into OpenAI deltas.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from ..model_card import ModelDeploymentCard
from ..protocols.common import (
    BackendInput,
    FinishReason,
    LLMEngineOutput,
    parse_priority,
)
from ..protocols.delta import ChatDeltaGenerator, CompletionDeltaGenerator
from ..protocols.openai import ChatCompletionRequest, CompletionRequest
from ..runtime.engine import AsyncEngine, AsyncEngineContext, ResponseStream
from ..runtime.pipeline import Operator
from ..telemetry import span as trace_span
from ..tokenizer import Tokenizer
from .prompt import PromptFormatter


class InvalidRequestError(ValueError):
    """Request parameters outside supported bounds (HTTP 400)."""


class PromptTooLongError(ValueError):
    """Prompt exceeds the model's context window (HTTP layer maps to 400)."""


class OpenAIPreprocessor(Operator):
    """Tokenizing/templating front half of the serving pipeline."""

    def __init__(self, mdc: ModelDeploymentCard, tokenizer: Tokenizer | None = None):
        self.mdc = mdc
        self.tokenizer = tokenizer or Tokenizer.from_pretrained(
            mdc.tokenizer_path or mdc.model_path
        )
        self.formatter = PromptFormatter(mdc)

    # --- request path -------------------------------------------------
    def preprocess_chat(self, request: ChatCompletionRequest) -> BackendInput:
        prompt = self.formatter.render(
            [m.model_dump(exclude_none=True) for m in request.messages],
            tools=request.tools,
        )
        return self._build_input(prompt, request, add_special_tokens=False)

    def preprocess_completion(self, request: CompletionRequest) -> BackendInput:
        prompt = request.prompt
        if isinstance(prompt, list) and len(prompt) == 1:
            prompt = prompt[0]
        if isinstance(prompt, str):
            return self._build_input(prompt, request, add_special_tokens=True)
        if isinstance(prompt, list) and all(isinstance(t, int) for t in prompt):
            return self._finish_input(list(prompt), request)
        raise ValueError(
            "multi-prompt batches must be expanded into per-prompt requests "
            "before preprocessing (the HTTP layer does this automatically)"
        )

    def _build_input(self, prompt: str, request, add_special_tokens: bool) -> BackendInput:
        ids = self.tokenizer.encode(prompt, add_special_tokens=add_special_tokens).ids
        return self._finish_input(ids, request)

    def _finish_input(self, token_ids: list[int], request) -> BackendInput:
        if len(token_ids) >= self.mdc.context_length:
            raise PromptTooLongError(
                f"prompt is {len(token_ids)} tokens but the model's context "
                f"length is {self.mdc.context_length}"
            )
        sampling = request.extract_sampling_options()
        if sampling.logprobs is not None:
            from ..ops.sampling import TOP_LOGPROBS

            if sampling.logprobs > TOP_LOGPROBS:
                # The device computes a static top-N per step; reject
                # rather than silently truncate the client's ask.
                raise InvalidRequestError(
                    f"top_logprobs={sampling.logprobs} exceeds the "
                    f"supported maximum of {TOP_LOGPROBS}"
                )
        stop = request.extract_stop_conditions()
        if not stop.stop_token_ids:
            stop.stop_token_ids = list(
                self.mdc.eos_token_ids or self.tokenizer.eos_token_ids
            )
        # Default generation budget: fill the remaining context.
        stop.apply_defaults(self.mdc.context_length - len(token_ids))
        try:
            priority = parse_priority(request.request_priority())
        except ValueError as e:
            raise InvalidRequestError(str(e)) from None
        return BackendInput(
            token_ids=token_ids,
            stop_conditions=stop,
            sampling_options=sampling,
            annotations=request.annotations(),
            priority=priority,
        )

    # --- pipeline operator --------------------------------------------
    def _top_map(self, tops: dict | None) -> dict[str, float]:
        """Legacy completions top_logprobs entry, keyed by decoded text.

        Distinct token ids can decode to the same string (partial-UTF-8
        byte tokens all render U+FFFD); keep the best logprob per string
        rather than letting dict insertion order silently drop
        alternatives.
        """
        out: dict[str, float] = {}
        for tid, lp in (tops or {}).items():
            s = self.tokenizer.decode([tid])
            if s not in out or lp > out[s]:
                out[s] = lp
        return out

    async def generate(
        self,
        request: Any,
        next_engine: AsyncEngine,
        context: AsyncEngineContext,
    ) -> ResponseStream:
        """Operator form: OpenAI request in, OpenAI chunks out."""
        if isinstance(request, dict):
            request = (
                ChatCompletionRequest.model_validate(request)
                if "messages" in request
                else CompletionRequest.model_validate(request)
            )
        is_chat = isinstance(request, ChatCompletionRequest)
        with trace_span("preprocess", chat=is_chat) as sp:
            backend_input = (
                self.preprocess_chat(request)
                if is_chat
                else self.preprocess_completion(request)
            )
            sp.set(prompt_tokens=len(backend_input.token_ids))
        want_usage = bool(request.stream_options and request.stream_options.include_usage)
        stream = await next_engine.generate(backend_input.to_dict(), context)
        gen = (
            ChatDeltaGenerator(request.model, context.id)
            if is_chat
            else CompletionDeltaGenerator(request.model, context.id)
        )
        prompt_tokens = len(backend_input.token_ids)

        want_logprobs = backend_input.sampling_options.logprobs is not None

        def _token_entry(tid: int, lp: float, tops: dict | None) -> dict:
            text = self.tokenizer.decode([tid])
            entry: dict = {
                "token": text,
                "logprob": lp,
                "bytes": list(text.encode("utf-8")),
            }
            if is_chat:
                entry["top_logprobs"] = [
                    {
                        "token": (t := self.tokenizer.decode([a])),
                        "logprob": alp,
                        "bytes": list(t.encode("utf-8")),
                    }
                    for a, alp in (tops or {}).items()
                ]
            return entry

        def _shape(raw: list) -> dict | None:
            """(tid, lp, tops) tuples → the OpenAI logprobs object."""
            if not raw:
                return None
            entries = [_token_entry(tid, lp, tp) for tid, lp, tp in raw]
            if is_chat:
                return {"content": entries}
            # Legacy completions shape.
            has_tops = any(tp for _, _, tp in raw)
            return {
                "tokens": [e["token"] for e in entries],
                "token_logprobs": [e["logprob"] for e in entries],
                "top_logprobs": [self._top_map(tp) for _, _, tp in raw]
                if has_tops
                else None,
            }

        async def _chunks() -> AsyncIterator[Any]:
            completion_tokens = 0
            finish: FinishReason | None = None
            # Logprob entries buffered until text flushes: a frame's
            # text may be withheld (partial UTF-8 in the detokenizer,
            # possible stop-sequence prefix in the jail) while its
            # tokens already produced logprobs — those entries ride the
            # NEXT emitted chunk instead of being dropped.
            pending: list = []
            async for item in stream:
                out = (
                    LLMEngineOutput.from_dict(item) if isinstance(item, dict) else item
                )
                completion_tokens += len(out.token_ids)
                if want_logprobs and out.logprobs:
                    tops = out.top_logprobs or [None] * len(out.logprobs)
                    pending += list(zip(out.token_ids, out.logprobs, tops))
                if out.text:
                    chunk = gen.text_chunk(out.text, _shape(pending))
                    # Sequence-index the chunk (cumulative token count)
                    # so the SSE layer can prove the stream gap-free and
                    # duplicate-free across mid-stream failovers.
                    chunk.seq_index = completion_tokens
                    yield chunk
                    pending = []
                if out.finish_reason is not None:
                    finish = FinishReason(out.finish_reason)
            if pending:  # logprobs whose text never flushed (e.g. stop)
                yield gen.text_chunk("", _shape(pending))
            yield gen.finish_chunk(finish or FinishReason.EOS)
            if want_usage:
                yield gen.usage_chunk(prompt_tokens, completion_tokens)

        return ResponseStream(_chunks(), context)
