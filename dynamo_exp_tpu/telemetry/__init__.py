"""End-to-end request tracing + per-stage latency telemetry.

One request through the stack yields a span tree — HTTP ingress →
preprocess → KV-router decision → (queue wait → prefill | remote
prefill → KV transfer) → decode — correlated by a contextvar-carried
``trace_id`` that also lands in JSONL log lines and rides the wire
across the request plane and the disagg protocol. See
``docs/observability.md``.
"""

from .anatomy import (
    COMPONENTS,
    AnatomyRing,
    RequestAnatomy,
    anatomy_from_flight,
    anatomy_from_spans,
    anatomy_from_timing,
    render_anatomy,
    render_slow,
)
from .context import (
    TraceContext,
    attach,
    current_span_id,
    current_trace,
    current_trace_id,
    detach,
    new_trace,
    wire_headers,
)
from .dispatch import DISPATCH_KINDS, DispatchProfiler
from .fingerprint import (
    FingerprintBuilder,
    WorkloadDriftWatch,
    WorkloadFingerprint,
    drift_score,
    fingerprint_from_bench,
    fingerprint_from_spans,
    fingerprint_from_trace,
    load_fingerprint,
    render_fingerprint,
    replay_workload,
)
from .fleet import (
    FleetAggregator,
    FleetView,
    InstanceView,
    TransferLedger,
    get_transfer_ledger,
    parse_prometheus_text,
    render_top,
)
from .flight import (
    FlightRecorder,
    Watchdog,
    dump_all,
    load_dumps,
    render_flight,
)
from .slo import BURN_WINDOWS, SloAttribution, SloConfig, percentile
from .spans import Span, Telemetry, adopt, get_telemetry, span
from .timeline import (
    find_trace,
    list_traces,
    load_spans,
    render_timeline,
    transfer_hops,
)

__all__ = [
    "BURN_WINDOWS",
    "COMPONENTS",
    "DISPATCH_KINDS",
    "AnatomyRing",
    "DispatchProfiler",
    "FingerprintBuilder",
    "FleetAggregator",
    "FleetView",
    "FlightRecorder",
    "InstanceView",
    "RequestAnatomy",
    "SloAttribution",
    "SloConfig",
    "Span",
    "Telemetry",
    "TraceContext",
    "TransferLedger",
    "Watchdog",
    "WorkloadDriftWatch",
    "WorkloadFingerprint",
    "adopt",
    "anatomy_from_flight",
    "anatomy_from_spans",
    "anatomy_from_timing",
    "attach",
    "current_span_id",
    "current_trace",
    "current_trace_id",
    "detach",
    "drift_score",
    "dump_all",
    "find_trace",
    "fingerprint_from_bench",
    "fingerprint_from_spans",
    "fingerprint_from_trace",
    "get_telemetry",
    "get_transfer_ledger",
    "list_traces",
    "load_dumps",
    "load_fingerprint",
    "load_spans",
    "new_trace",
    "parse_prometheus_text",
    "percentile",
    "render_anatomy",
    "render_fingerprint",
    "render_flight",
    "render_slow",
    "render_timeline",
    "render_top",
    "replay_workload",
    "span",
    "transfer_hops",
    "wire_headers",
]
