"""The TPU execution engine: continuous batching on a paged KV cache.

This replaces the reference's wrapped GPU engines (vLLM/sglang/TRT-LLM —
``/root/reference/lib/engines/``, SURVEY.md §2.3/§2.9) with an in-process
JAX engine:

- **Two small families of compiled programs** drive everything: decode
  *windows* (``lax.scan`` over ``decode_window`` steps with sampled
  tokens fed back on-device, keyed by attention impl / page bucket /
  sampler variant — one host sync per window, which is what survives a
  high-latency host↔device link) and batched chunked prefill (keyed by
  row bucket × token bucket × page bucket). Static shapes, no
  recompiles in steady state; KV pools are donated so XLA updates them
  in place in HBM.
- **The host loop is the scheduler** (reference's "hard part #3",
  SURVEY.md §7): stop flags, admissions, page allocation, and KV event
  emission all happen between steps on the loop thread — never inside a
  compiled region.
- **Prefix caching is free at the attention level**: reused pages are
  already resident; prefill just starts its positions after the cached
  prefix (write-then-gather attention reads them like any other page).
- **Tensor parallelism** comes from param/cache shardings over the
  engine's mesh; XLA inserts the ICI collectives.

The engine exposes the same ``AsyncEngine`` seam the rest of the stack
uses (``BackendInput`` dict in → ``LLMEngineOutput`` dict stream out), so
the preprocessor/backend/router layers are engine-agnostic, matching the
reference's ``ExecutionContext`` contract (``lib/llm/src/backend.rs:60``).
"""

from __future__ import annotations

import asyncio
import logging
import queue
import threading
import time
from functools import partial
from typing import AsyncIterator, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.config import ModelConfig
from ..models.llama import (
    Params,
    forward,
    init_kv_cache,
    init_params,
    kv_cache_shardings,
    param_shardings,
)
from ..ops.sampling import apply_penalties, sample_tokens, token_logprobs
from ..parallel.mesh import build_mesh
from ..protocols.common import BackendInput, FinishReason, LLMEngineOutput
from ..runtime.engine import AsyncEngine, AsyncEngineContext, ResponseStream
from ..telemetry import current_trace, get_telemetry
from .config import EngineConfig
from .kv_manager import KvEvent, KvPageManager
from .offload import CopyStream, HostKvPool
from .scheduler import RemoteKv, Scheduler, SeqState, Sequence

log = logging.getLogger(__name__)


class TPUEngine(AsyncEngine):
    """Continuous-batching paged-KV engine on a TPU mesh."""

    def __init__(
        self,
        cfg: EngineConfig,
        params: Params | None = None,
        mesh: Mesh | None = None,
        kv_event_cb: Callable[[KvEvent], None] | None = None,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.mesh = mesh or build_mesh(tp=cfg.tp, sp=cfg.sp)
        mcfg = cfg.model

        def sharding(spec):
            return NamedSharding(self.mesh, spec)

        if params is None:
            params = init_params(jax.random.PRNGKey(seed), mcfg)
        self.params = jax.device_put(
            params,
            jax.tree.map(
                sharding,
                param_shardings(mcfg),
                is_leaf=lambda x: isinstance(x, P),
            ),
        )
        kspec, vspec = kv_cache_shardings()
        k, v = init_kv_cache(
            mcfg, cfg.num_pages, cfg.page_size, dtype=cfg.kv_dtype_jnp
        )
        self.k_cache = jax.device_put(k, sharding(kspec))
        self.v_cache = jax.device_put(v, sharding(vspec))

        self.host_pool: HostKvPool | None = None
        self.copy_stream: CopyStream | None = None
        on_evict = None
        if cfg.host_cache_pages > 0:
            page_shape = (
                mcfg.num_layers,
                cfg.page_size,
                mcfg.num_kv_heads * mcfg.head_dim_,
            )
            self.host_pool = HostKvPool(
                cfg.host_cache_pages, page_shape, cfg.kv_dtype_jnp
            )

            # The CopyStream (a live thread) is created by start(), so a
            # constructed-but-never-started engine owns no threads.
            def on_evict(pid: int, seq_hash: int) -> None:
                # Dispatch the on-device gather now (stream order protects
                # it from the next donated forward); the CopyStream thread
                # blocks on the transfer and commits into the host pool.
                k_pg, v_pg = self._gather_page(self.k_cache, self.v_cache, pid)
                self.copy_stream.offload(seq_hash, k_pg, v_pg)

        self.kv = KvPageManager(
            cfg.num_pages,
            cfg.page_size,
            event_cb=kv_event_cb if cfg.enable_kv_events else None,
            host_pool=self.host_pool,
            on_evict=on_evict,
        )
        self.sched = Scheduler(cfg, self.kv)

        # Per-page movement kernels, shared by the G2 offload tier and
        # the disaggregation KV handoff (gather → wire / wire → inject).
        self._gather_page = jax.jit(lambda k, v, pid: (k[:, pid], v[:, pid]))
        self._inject_page = jax.jit(
            lambda k, v, pid, hk, hv: (
                k.at[:, pid].set(hk),
                v.at[:, pid].set(hv),
            ),
            donate_argnums=(0, 1),
        )

        B, V = cfg.max_decode_slots, mcfg.vocab_size
        self._counts = jnp.zeros((B, V), jnp.int32)  # penalty bookkeeping
        self._rng = jax.random.PRNGKey(seed + 1)
        self._attn_impl, self._attn_interpret = self._resolve_attn()
        # Compiled-variant caches. Decode windows are keyed by
        # (attention impl, static page bound — None on the Pallas path,
        # which reads true lengths — and full-vs-greedy sampler);
        # prefill by (row bucket, token bucket, page bound).
        self._decode_fns: dict[tuple, Callable] = {}
        self._prefill_fns: dict[tuple[int, int, int], Callable] = {}
        # Fresh penalty row for a slot: zero it, then count the first
        # sampled token so penalties see every generated token.
        self._init_row = jax.jit(
            lambda c, i, t: c.at[i].set(0).at[i, t].add(1),
            donate_argnums=(0,),
        )

        self._submit_q: queue.Queue[Sequence] = queue.Queue()
        self._wake = threading.Event()
        self._running = False
        self._thread: threading.Thread | None = None
        self.steps = 0  # decode step counter (metrics)
        self._last_gauge_pub = 0.0  # telemetry gauge throttle

    # ----------------------------------------------------------- compiled fns
    def _resolve_attn(self) -> tuple[str, bool]:
        """Pick the decode attention implementation. ``auto`` resolves to
        the ragged Pallas kernel only when the mesh actually sits on TPU
        (or ``pallas_interpret`` forces interpreter mode for CPU tests);
        anywhere else the length-bounded XLA gather is the correct
        choice. Layouts Mosaic can't tile (``pallas_supported``) fall
        back to XLA rather than fail at compile time on the first
        decode."""
        from ..ops.paged_decode import pallas_supported

        cfg = self.cfg
        impl = cfg.attention_impl
        interpret = cfg.pallas_interpret
        if impl == "auto":
            platform = self.mesh.devices.flat[0].platform
            impl = "pallas" if (platform == "tpu" or interpret) else "xla"
        mcfg = cfg.model
        if impl == "pallas" and (
            mcfg.sliding_window is not None
            or mcfg.attn_logit_softcap is not None
            or mcfg.query_pre_attn_scalar is not None
        ):
            # forward() would silently refuse the kernel for these
            # configs (window mask / softcap / scale live on the XLA
            # path); resolve xla HERE so attn_pages keeps bounding the
            # gather — otherwise decode would run the XLA path with an
            # unbounded Pmax-wide page table.
            impl = "xla"
        if impl == "pallas" and not interpret:
            tp = self.mesh.shape.get("tp", 1)
            if not pallas_supported(
                cfg.page_size,
                cfg.model.num_kv_heads // tp,
                cfg.model.head_dim_,
                cfg.kv_dtype_jnp,
            ):
                log.warning(
                    "KV layout (ps=%d, Hkv=%d/tp=%d, D=%d, %s) is not "
                    "Mosaic-tileable; decode falls back to the XLA path",
                    cfg.page_size,
                    cfg.model.num_kv_heads,
                    tp,
                    cfg.model.head_dim_,
                    cfg.kv_dtype,
                )
                impl = "xla"
        return impl, interpret

    def _decode_fn(
        self, attn_pages: int | None, full_sampler: bool, want_lp: bool
    ):
        """One compiled decode *window*: ``decode_window`` steps run
        on-device under ``lax.scan`` with sampled tokens fed straight
        back — the host syncs once per window instead of once per token,
        which is what makes decode throughput survive a high-latency
        host↔device link. ``full_sampler=False`` is the greedy fast
        path (no penalties, no top-k/p machinery) used whenever every
        stepped row is greedy.

        Even when the Pallas kernel is available, short contexts take
        the XLA gather: below ~1k tokens of page bucket the gather's
        HBM traffic is trivial and the kernel's serial per-row DMA grid
        costs more than it saves. The kernel wins where it matters —
        long contexts, where gather traffic scales with B*bucket while
        the kernel's scales with the true total context."""
        impl, interpret, mesh = self._attn_impl, self._attn_interpret, self.mesh
        if (
            impl == "pallas"
            and self.cfg.attention_impl == "auto"  # explicit pallas is honored
            and attn_pages * self.cfg.page_size <= 1024
        ):
            impl = "xla"
        pages = None if impl == "pallas" else attn_pages
        key = (impl, pages, full_sampler, want_lp)
        fn = self._decode_fns.get(key)
        if fn is not None:
            return fn
        mcfg = self.cfg.model
        K = self.cfg.decode_window

        @partial(jax.jit, donate_argnums=(1, 2, 8))
        def decode_window(params, k, v, tokens, positions, max_pos, page_table,
                          rng, counts, temp, top_k, top_p, freq_pen, pres_pen,
                          rep_pen):
            def step(carry, _):
                tokens, positions, k, v, rng, counts = carry
                logits, k, v = forward(
                    params, mcfg, tokens[:, None], positions[:, None],
                    page_table, k, v, attn_pages=pages, attn_impl=impl,
                    mesh=mesh, interpret=interpret,
                )
                logits = logits[:, 0]  # [B, V]
                if full_sampler:
                    shaped = apply_penalties(
                        logits, counts, freq_pen, pres_pen, rep_pen
                    )
                    rng2, sub = jax.random.split(rng)
                    next_tok = sample_tokens(shaped, sub, temp, top_k, top_p)
                else:
                    rng2 = rng
                    next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # OpenAI logprobs: of the MODEL distribution (raw
                # logits, pre-penalty/temperature), chosen + top-k.
                # Compiled only into the want_lp variant — the common
                # no-logprobs workload pays neither the full-vocab
                # log_softmax nor the extra per-window host transfer.
                if want_lp:
                    lp, top_ids, top_lp = token_logprobs(logits, next_tok)
                active = positions >= 0
                counts = counts.at[
                    jnp.arange(counts.shape[0]), next_tok
                ].add(active.astype(jnp.int32))
                # Feed the sampled token back; a row leaves the window
                # (position -1, writes dropped) once it hits its page /
                # model-length capacity.
                tokens = jnp.where(active, next_tok, tokens)
                positions = jnp.where(
                    active & (positions < max_pos), positions + 1, -1
                )
                ys = (
                    (next_tok, lp, top_ids, top_lp)
                    if want_lp
                    else (next_tok,)
                )
                return (tokens, positions, k, v, rng2, counts), ys

            (_, _, k, v, rng, counts), ys = jax.lax.scan(
                step, (tokens, positions, k, v, rng, counts), None, length=K
            )
            # ys: toks [K,B] (+ lp [K,B], top_ids/top_lp [K,B,N] when
            # want_lp).
            return ys, k, v, rng, counts

        self._decode_fns[key] = decode_window
        return decode_window

    def _prefill_fn(
        self, rows: int, bucket: int, attn_pages: int, want_lp: bool
    ):
        key = (rows, bucket, attn_pages, want_lp)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        mcfg = self.cfg.model

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_step(params, k, v, tokens, positions, page_table, rng,
                         last_idx, temp, top_k, top_p):
            logits, k, v = forward(
                params, mcfg, tokens, positions, page_table, k, v,
                attn_pages=attn_pages, last_positions=last_idx,
            )
            rng, sub = jax.random.split(rng)
            toks = sample_tokens(logits[:, 0], sub, temp, top_k, top_p)
            if want_lp:
                lp, top_ids, top_lp = token_logprobs(logits[:, 0], toks)
                return (toks, lp, top_ids, top_lp), k, v, rng
            return (toks,), k, v, rng

        self._prefill_fns[key] = prefill_step
        return prefill_step

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._running:
            return
        if self.host_pool is not None and self.copy_stream is None:
            # stop() tears the copy stream down; a restarted engine needs
            # a live one before the first eviction fires on_evict.
            self.copy_stream = CopyStream(self.host_pool)
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="tpu-engine-loop", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=30)
            self._thread = None
        if self.copy_stream is not None:
            self.copy_stream.stop()
            self.copy_stream = None

    # ------------------------------------------------------------ AsyncEngine
    async def generate(
        self,
        request: dict | BackendInput,
        context: AsyncEngineContext | None = None,
        remote_kv: RemoteKv | None = None,
    ) -> ResponseStream[dict]:
        if not self._running:
            self.start()
        ctx = context or AsyncEngineContext()
        binput = (
            request
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()

        def emit(
            tokens: list[int],
            reason: FinishReason | None,
            logprobs=None,  # (lps: list[float], tops: list[dict]) | None
        ) -> None:
            loop.call_soon_threadsafe(
                out_q.put_nowait, (tokens, reason, logprobs)
            )

        seq = Sequence(
            request_id=ctx.id,
            prompt=list(binput.token_ids),
            stop=binput,
            emit=emit,
            is_cancelled=lambda: ctx.is_stopped,
            remote_kv=remote_kv,
            trace=current_trace(),
            submitted_at=time.time(),
        )
        self._submit_q.put(seq)
        self._wake.set()
        prompt_tokens = len(binput.token_ids)

        async def _gen() -> AsyncIterator[dict]:
            completion = 0
            while True:
                tokens, reason, logprobs = await out_q.get()
                if tokens:
                    completion += len(tokens)
                    yield LLMEngineOutput(
                        token_ids=tokens,
                        logprobs=logprobs[0] if logprobs else None,
                        top_logprobs=logprobs[1] if logprobs else None,
                    ).to_dict()
                if reason is not None:
                    yield LLMEngineOutput(
                        finish_reason=reason,
                        prompt_tokens=prompt_tokens,
                        completion_tokens=completion,
                    ).to_dict()
                    return

        return ResponseStream(_gen(), ctx)

    async def prefill_extract(
        self,
        request: dict | BackendInput,
        context: AsyncEngineContext | None = None,
    ) -> tuple[int, list]:
        """Run prefill only and hand back (first_token, kv_pages).

        This is the prefill-worker side of disaggregation: the prompt's
        KV pages (host-bounced numpy, one (k, v) pair per page) travel to
        the decode worker, which injects them via ``generate(...,
        remote_kv=...)``. The pages also stay registered locally, so
        repeated prompts prefix-hit this worker's pool.
        """
        if not self._running:
            self.start()
        ctx = context or AsyncEngineContext()
        binput = (
            request.model_copy(deep=True)  # never mutate the caller's object
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        binput.stop_conditions.max_tokens = 1  # prefill produces one token
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def extract_cb(token: int, pages: list) -> None:
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result((token, pages))
            )

        def emit(
            tokens: list[int], reason: FinishReason | None, logprobs=None
        ) -> None:
            if reason in (FinishReason.ERROR, FinishReason.CANCELLED):
                loop.call_soon_threadsafe(
                    lambda: fut.done()
                    or fut.set_exception(RuntimeError(f"prefill failed: {reason}"))
                )

        seq = Sequence(
            request_id=ctx.id,
            prompt=list(binput.token_ids),
            stop=binput,
            emit=emit,
            is_cancelled=lambda: ctx.is_stopped,
            extract_cb=extract_cb,
            trace=current_trace(),
            submitted_at=time.time(),
        )
        self._submit_q.put(seq)
        self._wake.set()
        return await fut

    # -------------------------------------------------------------- the loop
    def _loop(self) -> None:
        """One iteration = admit everything admissible, dispatch at most
        one batched prefill chunk, then one decode step — so decode
        interleaves between the chunks of long prompts instead of
        stalling behind them (scheduler v2 policy, ``scheduler.py``
        module docstring)."""
        try:
            while self._running:
                if not self.sched.has_work() and self._submit_q.empty():
                    # Publish on the idle path too: the gauges must decay
                    # to zero after the last request finishes, not freeze
                    # on the final busy-loop snapshot.
                    self._maybe_publish_gauges()
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                self._drain_submissions()
                self._poll_cancellations()
                while (admitted := self.sched.admit_next()) is not None:
                    self._on_admitted(admitted)
                self._maybe_publish_gauges()
                progressed = False
                prefilling = [
                    s
                    for s in self.sched.slots
                    if s is not None and s.state is SeqState.PREFILL
                ]
                # Partition the snapshot BEFORE injecting: injection
                # clears remote_kv and promotes the sequence to ACTIVE,
                # so filtering afterwards would re-prefill it.
                batch = [s for s in prefilling if s.remote_kv is None]
                for seq in prefilling:
                    if seq.remote_kv is not None:
                        self._run_remote_inject(seq)
                        progressed = True
                if batch:
                    self._run_prefill_chunk(batch[: self.cfg.prefill_batch])
                    progressed = True
                if any(
                    s is not None and s.state is SeqState.ACTIVE
                    for s in self.sched.slots
                ):
                    progressed = self._run_decode() or progressed
                if not progressed:
                    # Pool dry / everything stalled: yield briefly.
                    self._wake.wait(timeout=0.001)
                    self._wake.clear()
        except Exception:  # engine death must not hang clients
            log.exception("engine loop crashed; failing in-flight requests")
            self._running = False
            self._fail_all()
            raise

    def _on_admitted(self, seq: Sequence) -> None:
        """Close the request's queue-wait stage (submission -> slot +
        pages bound). Runs on the engine loop thread with the trace
        captured at submission."""
        now = time.time()
        seq.admitted_at = now
        tel = get_telemetry()
        if seq.submitted_at:
            tel.queue_wait.observe(max(now - seq.submitted_at, 0.0))
            tel.emit_stage(
                "queue_wait",
                seq.submitted_at,
                now,
                seq.trace,
                prompt_tokens=len(seq.prompt),
            )

    def _maybe_publish_gauges(self) -> None:
        """Mirror engine gauges into the telemetry registry at most
        ~2x/second — the loop can spin thousands of times faster."""
        now = time.monotonic()
        if now - self._last_gauge_pub >= 0.5:
            self._last_gauge_pub = now
            get_telemetry().publish_engine_gauges(self.metrics())

    def _drain_submissions(self) -> None:
        while True:
            try:
                self.sched.submit(self._submit_q.get_nowait())
            except queue.Empty:
                return

    def _poll_cancellations(self) -> None:
        for s in list(self.sched.slots):
            if s is not None and s.is_cancelled():
                self.sched.finish(s, FinishReason.CANCELLED)

    def _fail_all(self) -> None:
        for s in list(self.sched.slots):
            if s is not None:
                self.sched.finish(s, FinishReason.ERROR)
        while self.sched.waiting:
            s = self.sched.waiting.popleft()
            s.emit([], FinishReason.ERROR)
        while not self._submit_q.empty():
            try:
                self._submit_q.get_nowait().emit([], FinishReason.ERROR)
            except queue.Empty:
                break

    # ---------------------------------------------------------------- prefill
    def _apply_uploads(self, seq: Sequence) -> None:
        """Re-inject G2 host pages into their fresh device pages before
        the compute that attends over them (dispatch order on the device
        stream makes this safe without explicit sync)."""
        for pid, _h, hk, hv in seq.pending_uploads:
            self.k_cache, self.v_cache = self._inject_page(
                self.k_cache, self.v_cache, pid, jnp.asarray(hk), jnp.asarray(hv)
            )
        seq.pending_uploads = []

    @staticmethod
    def _wants_logprobs(seq: Sequence) -> int | None:
        """The request's top_logprobs count (0 = chosen only), or None."""
        return seq.stop.sampling_options.logprobs

    @staticmethod
    def _lp_pack(n_top: int, lps, top_ids, top_lps):
        """Host-side logprob payload for emit: per-token chosen logprob
        plus the top-n alternatives (n sliced from the static TOP_LOGPROBS
        the device computes)."""
        tops = None
        if n_top > 0:
            tops = [
                {int(t): float(l) for t, l in zip(tid[:n_top], tlp[:n_top])}
                for tid, tlp in zip(top_ids, top_lps)
            ]
        return ([float(x) for x in lps], tops)

    def _finish_first_token(
        self, seq: Sequence, token: int, lp_pack=None
    ) -> None:
        """Shared tail of the two admission paths (computed prefill or
        remote-KV injection): record + announce the first sampled token
        and promote the sequence to decode. ``lp_pack`` is None on the
        remote-KV path — the first token was sampled on the prefill
        worker, which doesn't ship its distribution."""
        now = time.time()
        seq.first_token_at = seq.last_emit_at = now
        tel = get_telemetry()
        start = seq.admitted_at or seq.submitted_at or now
        tel.prefill_compute.observe(max(now - start, 0.0))
        tel.emit_stage(
            "prefill",
            start,
            now,
            seq.trace,
            prompt_tokens=len(seq.prompt),
            cached_tokens=seq.cached_len,
            remote=seq.remote_prefilled or None,
        )
        seq.state = SeqState.ACTIVE
        self._counts = self._init_row(self._counts, seq.slot, token)
        seq.tokens.append(token)
        seq.generated = 1
        self.sched.register_full_pages(seq)
        if seq.extract_cb is not None:
            seq.extract_cb(token, self._extract_prompt_pages(seq))
        reason = self.sched.check_stop(seq, token)
        seq.emit([token], None, lp_pack)
        if reason is not None:
            self.sched.finish(seq, reason)

    def _extract_prompt_pages(self, seq: Sequence) -> list:
        """Host-bounce every prompt page (incl. the partial tail) for the
        disaggregation handoff. Runs on the engine loop thread: the
        prefill worker's job is exactly this transfer."""
        ps = self.cfg.page_size
        n_pages = (len(seq.prompt) + ps - 1) // ps
        pages = []
        for pid in seq.page_ids[:n_pages]:
            k_pg, v_pg = self._gather_page(self.k_cache, self.v_cache, pid)
            pages.append((np.asarray(k_pg), np.asarray(v_pg)))
        return pages

    def _run_remote_inject(self, seq: Sequence) -> None:
        """Disaggregated admission: prompt KV was computed by a remote
        prefill worker — inject it and go straight to decode."""
        self._apply_uploads(seq)
        ps = self.cfg.page_size
        rk = seq.remote_kv
        n_pages = (len(seq.prompt) + ps - 1) // ps
        start = seq.cached_len // ps  # locally matched/uploaded prefix
        for i in range(start, min(n_pages, len(rk.pages))):
            hk, hv = rk.pages[i]
            self.k_cache, self.v_cache = self._inject_page(
                self.k_cache,
                self.v_cache,
                seq.page_ids[i],
                jnp.asarray(hk),
                jnp.asarray(hv),
            )
        seq.remote_kv = None  # drop the host copy the moment it's injected
        seq.remote_prefilled = True
        self._finish_first_token(seq, rk.first_token)

    def _run_prefill_chunk(self, batch: list[Sequence]) -> None:
        """One batched prefill dispatch: up to ``prefill_batch`` PREFILL
        sequences each contribute their next ``prefill_chunk``-token
        slice of prompt. Rows/tokens are bucketed so steady state hits a
        small set of compiled variants; rows whose prompt completes this
        chunk get their first token sampled (per-row sampling params) and
        graduate to decode."""
        cfg = self.cfg
        ps = cfg.page_size
        rows = cfg.rows_bucket_for(len(batch))
        sizes = [
            min(len(s.prompt) - s.prefill_sent, cfg.prefill_chunk)
            for s in batch
        ]
        bucket = cfg.bucket_for(max(sizes))
        tokens = np.zeros((rows, bucket), np.int32)
        positions = np.full((rows, bucket), -1, np.int32)
        table = np.zeros((rows, cfg.max_pages_per_seq), np.int32)
        last_idx = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.ones(rows, np.float32)
        completed: list[tuple[int, Sequence]] = []
        for i, seq in enumerate(batch):
            self._apply_uploads(seq)
            n = sizes[i]
            start = seq.prefill_sent
            tokens[i, :n] = seq.prompt[start : start + n]
            positions[i, :n] = np.arange(start, start + n)
            table[i, : len(seq.page_ids)] = seq.page_ids
            last_idx[i] = n - 1
            seq.prefill_sent = start + n
            if seq.prefill_sent == len(seq.prompt):
                completed.append((i, seq))
            so = seq.stop.sampling_options
            temp[i] = so.temperature if so.temperature is not None else 0.0
            top_k[i] = so.top_k or 0
            top_p[i] = so.top_p if so.top_p is not None else 1.0

        attn_pages = cfg.page_bucket_for(
            max((s.prefill_sent + ps - 1) // ps for s in batch)
        )
        want_lp = any(
            self._wants_logprobs(seq) is not None for seq in batch
        )
        fn = self._prefill_fn(rows, bucket, attn_pages, want_lp)
        ys, self.k_cache, self.v_cache, self._rng = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(table),
            self._rng,
            jnp.asarray(last_idx),
            jnp.asarray(temp),
            jnp.asarray(top_k),
            jnp.asarray(top_p),
        )
        if completed:
            if want_lp:
                toks, lps, top_ids, top_lps = (np.asarray(y) for y in ys)
            else:
                toks = np.asarray(ys[0])
            for i, seq in completed:
                n_top = self._wants_logprobs(seq)
                pack = (
                    self._lp_pack(
                        n_top, lps[i : i + 1],
                        top_ids[i : i + 1], top_lps[i : i + 1],
                    )
                    if want_lp and n_top is not None
                    else None
                )
                self._finish_first_token(seq, int(toks[i]), pack)

    # ----------------------------------------------------------------- decode
    def _run_decode(self) -> bool:
        """One decode *window* (``decode_window`` on-device steps, one
        host sync) over every ACTIVE slot. Returns False when nothing
        could step (page pool dry)."""
        cfg = self.cfg
        ps = cfg.page_size
        B = cfg.max_decode_slots
        K = cfg.decode_window
        tokens = np.zeros(B, np.int32)
        positions = np.full(B, -1, np.int32)
        max_pos = np.full(B, -1, np.int32)
        table = np.zeros((B, cfg.max_pages_per_seq), np.int32)
        temp = np.zeros(B, np.float32)
        top_k = np.zeros(B, np.int32)
        top_p = np.ones(B, np.float32)
        freq = np.zeros(B, np.float32)
        pres = np.zeros(B, np.float32)
        rep = np.ones(B, np.float32)

        stepped: list[tuple[Sequence, int]] = []  # (seq, valid steps)
        max_pages = 1
        full_sampler = False
        for i, seq in enumerate(self.sched.slots):
            if seq is None or seq.state is not SeqState.ACTIVE:
                continue
            wpos = len(seq.tokens) - 1  # position of the token being fed
            # Provision the whole window up front (best effort: partial
            # allocation still lets the row run until its pages end).
            self.sched.ensure_pages_until(seq, wpos + K - 1)
            cap = min(cfg.max_model_len, len(seq.page_ids) * ps) - 1
            if cap < wpos:
                seq.stalled = True
                continue  # pool dry: this slot idles one window
            seq.stalled = len(seq.page_ids) * ps < min(
                wpos + K, cfg.max_model_len
            )
            tokens[i] = seq.last_token()
            positions[i] = wpos
            max_pos[i] = cap
            table[i, : len(seq.page_ids)] = seq.page_ids
            max_pages = max(max_pages, (min(wpos + K, cap + 1) + ps - 1) // ps)
            so = seq.stop.sampling_options
            temp[i] = so.temperature if so.temperature is not None else 0.0
            top_k[i] = so.top_k or 0
            top_p[i] = so.top_p if so.top_p is not None else 1.0
            freq[i] = so.frequency_penalty or 0.0
            pres[i] = so.presence_penalty or 0.0
            rep[i] = so.repetition_penalty or 1.0
            if temp[i] > 0.0 or freq[i] or pres[i] or rep[i] != 1.0:
                full_sampler = True
            stepped.append((seq, min(K, cap - wpos + 1)))
        if not stepped:
            return False

        want_lp = any(
            self._wants_logprobs(seq) is not None for seq, _ in stepped
        )
        fn = self._decode_fn(
            cfg.page_bucket_for(max_pages), full_sampler, want_lp
        )
        ys, self.k_cache, self.v_cache, self._rng, self._counts = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(max_pos),
            jnp.asarray(table),
            self._rng,
            self._counts,
            jnp.asarray(temp),
            jnp.asarray(top_k),
            jnp.asarray(top_p),
            jnp.asarray(freq),
            jnp.asarray(pres),
            jnp.asarray(rep),
        )
        self.steps += K
        # [K, B] (+ [K, B, N] tops when want_lp) — one sync per window.
        if want_lp:
            sampled, lps, top_ids, top_lps = (np.asarray(y) for y in ys)
        else:
            sampled = np.asarray(ys[0])
        for seq, n_valid in stepped:
            kept: list[int] = []
            reason = None
            for token in sampled[:n_valid, seq.slot]:
                token = int(token)
                kept.append(token)
                seq.tokens.append(token)
                seq.generated += 1
                reason = self.sched.check_stop(seq, token)
                if reason is not None:
                    break
            self.sched.register_full_pages(seq)
            n_top = self._wants_logprobs(seq)
            pack = None
            if n_top is not None and kept:
                n = len(kept)
                pack = self._lp_pack(
                    n_top,
                    lps[:n, seq.slot],
                    top_ids[:n, seq.slot],
                    top_lps[:n, seq.slot],
                )
            if kept:
                now = time.time()
                if seq.last_emit_at:
                    tbt = max(now - seq.last_emit_at, 0.0) / len(kept)
                    get_telemetry().time_between_tokens.observe(tbt)
                seq.last_emit_at = now
            seq.emit(kept, None, pack)
            if reason is not None:
                self.sched.finish(seq, reason)
        return True

    # --------------------------------------------------------------- metrics
    def metrics(self) -> dict:
        m = self.sched.metrics()
        if self.host_pool is not None:
            m["host_cache_resident"] = self.host_pool.resident
            m["host_cache_hits"] = self.host_pool.hits
            m["host_cache_stores"] = self.host_pool.stores
        return m
