"""Native tier: the C++ blockhash extension and its bit-exact Python
mirror must agree — router and worker processes key prefix identity on
these hashes, so the two implementations disagreeing would silently
break cache reuse across processes."""

import numpy as np
import pytest

from dynamo_exp_tpu import native
from dynamo_exp_tpu.tokens import (
    DEFAULT_HASH_SEED,
    compute_block_hash,
    compute_block_hashes_for_seq,
    chain_hash,
)


def test_extension_builds_and_loads():
    # g++ is part of the image; the extension must actually build here.
    assert native.native_available()


def test_cpp_matches_python_mirror():
    rs = np.random.RandomState(0)
    for n in (1, 7, 16, 64, 300):
        toks = rs.randint(0, 2**31, size=n).tolist()
        for seed in (0, 1337, 2**63):
            assert native.block_hash(toks, seed) == native._py_block_hash(
                toks, seed
            )
    local = native.block_hash([1, 2, 3], 1337)
    for parent in (None, 0, 1, 2**64 - 1, local):
        assert native.chain_hash(parent, local, 1337) == native._py_chain_hash(
            parent, local, 1337
        )


def test_batch_seq_hashes_match_blockwise_loop():
    rs = np.random.RandomState(1)
    toks = rs.randint(0, 2**31, size=67).tolist()  # 4 full blocks of 16 + tail
    batch = native.seq_hashes(toks, 16, DEFAULT_HASH_SEED)
    loop = []
    parent = None
    for start in range(0, len(toks) - 15, 16):
        local = compute_block_hash(toks[start : start + 16])
        parent = chain_hash(parent, local)
        loop.append(parent)
    assert batch == loop == compute_block_hashes_for_seq(toks, 16)
    assert len(batch) == 4


def test_hash_properties():
    # Equal prefixes -> equal sequence hashes; diverging block -> different.
    a = list(range(64))
    b = list(range(48)) + [999] * 16
    ha = compute_block_hashes_for_seq(a, 16)
    hb = compute_block_hashes_for_seq(b, 16)
    assert ha[:3] == hb[:3]
    assert ha[3] != hb[3]
    # Parent participates: same block content, different prefix.
    assert chain_hash(ha[0], 42) != chain_hash(hb[3], 42)
    assert chain_hash(None, 42) != chain_hash(0, 42)  # None is not 0
    # Seed participates.
    assert compute_block_hash([1, 2, 3], 1) != compute_block_hash([1, 2, 3], 2)
    # Length participates (trailing content vs shorter block).
    assert compute_block_hash([1, 2]) != compute_block_hash([1, 2, 0])


def test_incomplete_block_yields_nothing():
    assert compute_block_hashes_for_seq([1, 2, 3], 16) == []
    assert native.seq_hashes([], 16, 1337) == []
