"""``depends()``: graph edges that resolve to live clients at runtime.

Reference parity: ``deploy/dynamo/sdk/lib/dependency.py`` — a class
attribute ``dep = depends(Other)`` both declares the edge (so the serve
CLI launches ``Other``) and, inside a running service, behaves as a
client of ``Other``'s endpoints.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator

logger = logging.getLogger(__name__)


class DependencyClient:
    """Callable proxy for one remote endpoint of a dependency."""

    def __init__(self, push_router, endpoint_path: str, ready_timeout_s: float = 30.0):
        self._router = push_router
        self.endpoint_path = endpoint_path
        self.ready_timeout_s = ready_timeout_s

    async def generate(self, request: dict) -> AsyncIterator[Any]:
        """Send one request; returns the response stream (data frames).

        Waits for at least one live instance first: graph services boot
        concurrently, so a dependency may come up moments after its
        dependents (reference: ``wait_for_endpoints``)."""
        if not self._router.client.instances:
            await self._router.client.wait_for_instances(1, self.ready_timeout_s)
        return await self._router.generate(request)

    async def round_robin(self, request: dict) -> AsyncIterator[Any]:
        return await self.generate(request)

    async def wait_ready(self, n: int = 1, timeout_s: float | None = None) -> None:
        """Block until ``n`` live instances exist (graph services boot
        concurrently; dependents gate first use on this)."""
        if len(self._router.client.instance_ids()) >= n:
            return
        await self._router.client.wait_for_instances(
            n, timeout_s if timeout_s is not None else self.ready_timeout_s
        )

    async def direct(self, request: dict, instance_id: int) -> AsyncIterator[Any]:
        return await self._router.generate_direct(request, instance_id)

    def instance_ids(self) -> list[int]:
        return self._router.client.instance_ids()


class depends:  # noqa: N801 - mirrors the reference's lowercase API
    """Declare a dependency on another @service class.

    As a class attribute it is inert metadata; ``resolve()`` (called by
    the serving layer) binds it to a live client. Accessing it from an
    instance before resolution raises, which catches un-served usage.
    """

    def __init__(self, target: type, endpoint: str = "generate"):
        self.target = target
        self.endpoint_name = endpoint
        self._client: DependencyClient | None = None

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if self._client is None:
            raise RuntimeError(
                f"dependency on {self.target.__name__} not resolved — are you "
                "running outside `python -m dynamo_exp_tpu.sdk.serve`?"
            )
        return self._client

    async def resolve(self, drt) -> DependencyClient:
        """Bind to the dependency's endpoint via the request plane."""
        from ..runtime.push_router import PushRouter, RouterMode
        from .service import get_spec

        spec = get_spec(self.target)
        ep = (
            drt.namespace(spec.namespace)
            .component(spec.component_name)
            .endpoint(self.endpoint_name)
        )
        client = await ep.client()
        self._client = DependencyClient(
            PushRouter(client, RouterMode.ROUND_ROBIN), ep.path
        )
        return self._client
