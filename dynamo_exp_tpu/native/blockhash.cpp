// Chained block hashing for token sequences — the native hot path under
// the KV-aware router and the prefix-reuse cache.
//
// Reference capability: the token/block layer is native Rust there
// (`/root/reference/lib/tokens/src/lib.rs:44-369`, xxh3-based); here the
// algorithm is a splitmix64-finalizer chain chosen so the Python
// fallback (`native/__init__.py`) can mirror it EXACTLY — equal inputs
// must give equal hashes whether or not the extension built, or router
// and worker processes would disagree on prefix identity.
//
// Layout contract (mirrored in Python — change both or neither):
//   mix(x)            = splitmix64 finalizer
//   local(toks, seed) = mix(seed ^ LOCAL_TAG) folded over
//                       mix(h ^ (tok + GOLDEN)), closed with mix(h ^ n)
//   chain(parent?, local, seed)
//                     = mix(seed ^ CHAIN_TAG) -> mix(h ^ parent-or-TAG)
//                       -> mix(h ^ local)

#include <cstdint>
#include <cstddef>

static const uint64_t GOLDEN = 0x9e3779b97f4a7c15ULL;
static const uint64_t LOCAL_TAG = 0x00b10c4a54aa17e5ULL;
static const uint64_t CHAIN_TAG = 0x00c4a18a54bb28f6ULL;
static const uint64_t NO_PARENT_TAG = 0x006e6f5061726e74ULL;

static inline uint64_t mix64(uint64_t x) {
    x ^= x >> 30;
    x *= 0xbf58476d1ce4e5b9ULL;
    x ^= x >> 27;
    x *= 0x94d049bb133111ebULL;
    x ^= x >> 31;
    return x;
}

extern "C" {

uint64_t dx_block_hash(const uint32_t* toks, uint64_t n, uint64_t seed) {
    uint64_t h = mix64(seed ^ LOCAL_TAG);
    for (uint64_t i = 0; i < n; ++i) {
        h = mix64(h ^ ((uint64_t)toks[i] + GOLDEN));
    }
    return mix64(h ^ n);
}

uint64_t dx_chain_hash(uint64_t parent, int has_parent, uint64_t local,
                       uint64_t seed) {
    uint64_t h = mix64(seed ^ CHAIN_TAG);
    h = mix64(h ^ (has_parent ? parent : NO_PARENT_TAG));
    return mix64(h ^ local);
}

// Sequence hashes for every complete block; returns the block count.
// seq_out must hold n / block entries.
uint64_t dx_seq_hashes(const uint32_t* toks, uint64_t n, uint64_t block,
                       uint64_t seed, int has_parent, uint64_t parent,
                       uint64_t* seq_out) {
    uint64_t nb = block ? n / block : 0;
    for (uint64_t b = 0; b < nb; ++b) {
        uint64_t local = dx_block_hash(toks + b * block, block, seed);
        parent = dx_chain_hash(parent, has_parent, local, seed);
        has_parent = 1;
        seq_out[b] = parent;
    }
    return nb;
}

}  // extern "C"
