"""Endpoint client: tracks live instances and issues streaming requests.

Capability parity with ``/root/reference/lib/runtime/src/component/client.rs``:
a dynamic client watches discovery for membership changes (lease expiry
drops instances instantly); a static client uses a fixed instance list.
Routing policies live in :mod:`push_router`.

Every client owns a :class:`~dynamo_exp_tpu.runtime.health.HealthTracker`:
discovery snapshots stamp liveness into it here, request outcomes are
recorded into it by the router. The discovery watch loop survives stream
errors — it logs, re-subscribes with capped exponential backoff, and
re-lists instances on resume so a flapping control plane degrades to a
slightly stale view instead of a silently frozen one.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import AsyncIterator

from .annotated import Annotated
from .engine import AsyncEngineContext
from .health import HealthTracker
from .runtime import Runtime
from .transports.base import Discovery, InstanceInfo, RequestPlane

logger = logging.getLogger(__name__)

# Watch-resubscribe backoff bounds (seconds).
_WATCH_BACKOFF_INITIAL_S = 0.05
_WATCH_BACKOFF_MAX_S = 2.0


class Client:
    def __init__(
        self, request_plane: RequestPlane, health: HealthTracker | None = None
    ):
        self.request_plane = request_plane
        self.health = health or HealthTracker()
        self._instances: list[InstanceInfo] = []
        self._changed = asyncio.Event()
        self._watch_task: asyncio.Task | None = None

    # --- construction -------------------------------------------------
    @classmethod
    def new_static(
        cls,
        request_plane: RequestPlane,
        instances: list[InstanceInfo],
        health: HealthTracker | None = None,
    ) -> "Client":
        c = cls(request_plane, health=health)
        c._apply_snapshot(list(instances))
        return c

    @classmethod
    async def new_dynamic(
        cls,
        runtime: Runtime,
        discovery: Discovery,
        request_plane: RequestPlane,
        endpoint_path: str,
        health: HealthTracker | None = None,
    ) -> "Client":
        c = cls(request_plane, health=health)

        async def _watch() -> None:
            backoff = _WATCH_BACKOFF_INITIAL_S
            while True:
                try:
                    async for snapshot in discovery.watch_instances(endpoint_path):
                        backoff = _WATCH_BACKOFF_INITIAL_S
                        c._apply_snapshot(snapshot)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 - watch must survive
                    logger.warning(
                        "discovery watch for %s failed (%s: %s); "
                        "re-subscribing in %.2fs",
                        endpoint_path, type(e).__name__, e, backoff,
                    )
                else:
                    # The stream ended without error (control plane closed
                    # it); treat like a flap and re-subscribe.
                    logger.warning(
                        "discovery watch for %s ended; re-subscribing in %.2fs",
                        endpoint_path, backoff,
                    )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, _WATCH_BACKOFF_MAX_S)
                # Re-list on resume: membership changes during the gap
                # produced no watch push, so the snapshot must be pulled.
                with contextlib.suppress(Exception):
                    c._apply_snapshot(
                        await discovery.list_instances(endpoint_path)
                    )

        c._apply_snapshot(await discovery.list_instances(endpoint_path))
        c._watch_task = runtime.spawn(_watch())
        return c

    # --- membership ---------------------------------------------------
    def _apply_snapshot(self, snapshot: list[InstanceInfo]) -> None:
        self._instances = snapshot
        self.health.observe_instances(snapshot)
        self._changed.set()

    @property
    def instances(self) -> list[InstanceInfo]:
        return self._instances

    def instance_ids(self) -> list[int]:
        return [i.instance_id for i in self._instances]

    async def wait_for_instances(self, n: int = 1, timeout: float | None = None) -> None:
        async def _wait() -> None:
            while len(self._instances) < n:
                self._changed.clear()
                await self._changed.wait()

        await asyncio.wait_for(_wait(), timeout)

    def instance(self, instance_id: int) -> InstanceInfo:
        for i in self._instances:
            if i.instance_id == instance_id:
                return i
        raise KeyError(f"instance {instance_id} is not live")

    # --- requests -----------------------------------------------------
    async def generate_to(
        self,
        instance: InstanceInfo,
        request: dict,
        context: AsyncEngineContext | None = None,
    ) -> AsyncIterator[Annotated]:
        """Issue a request to one instance; yields Annotated frames.

        Error frames raise ``EngineError`` so callers see remote failures
        as exceptions unless they iterate the raw stream themselves.
        """
        ctx = context or AsyncEngineContext()
        frames = await self.request_plane.request_stream(instance, request, ctx)

        async def _gen() -> AsyncIterator[Annotated]:
            async for frame in frames:
                ann = Annotated.from_dict(frame)
                if ann.is_error():
                    raise EngineError(ann.error_message() or "remote engine error")
                yield ann

        return _gen()

    async def open_stream(
        self,
        instance: InstanceInfo,
        request: dict,
        context: AsyncEngineContext | None = None,
    ) -> tuple[Annotated | None, AsyncIterator[Annotated]]:
        """Dispatch and eagerly pull the stream's first frame, so
        stream-start failures surface to the caller's retry loop as
        exceptions *here* rather than mid-iteration.

        Returns ``(first, rest)``; ``first`` is None for a clean empty
        stream. An in-band error in the first frame is *returned* (not
        raised): it means the stream started — an application failure,
        outside the failover contract. The push router uses this for
        both the initial dispatch and resumable-stream continuation
        re-dispatches."""
        frames = await self.generate_to(instance, request, context)
        try:
            first: Annotated | None = await anext(aiter(frames))
        except StopAsyncIteration:
            first = None
        except EngineError as e:
            # generate_to raises for error frames; fold the first-frame
            # case back into a frame so retry loops' ConnectionError
            # filters stay precise.
            first = Annotated.from_error(str(e))
        return first, frames

    def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()


class EngineError(RuntimeError):
    """A remote engine reported an error frame in its response stream."""
