"""Worker-side publishers: KV events and load metrics.

Capability parity with ``/root/reference/lib/llm/src/kv_router/publisher.rs``
(:34-139): ``KvEventPublisher`` forwards the engine's page-manager events
onto the event plane attributed to this worker; ``KvMetricsPublisher``
serves ``ForwardPassMetrics`` as the endpoint's stats handler.

Thread-safety note: the TPU engine emits events from its loop *thread*;
the publisher hops them onto the asyncio loop with
``run_coroutine_threadsafe`` — the single-writer boundary between the
device-driving thread and the serving loop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable

from ..engine.kv_manager import KvEvent
from .protocols import (
    ForwardPassMetrics,
    KvCacheEventData,
    RouterEvent,
    kv_events_subject,
)

logger = logging.getLogger(__name__)


class KvEventPublisher:
    def __init__(
        self,
        event_plane,
        component_path: str,
        worker_id: int,
        loop: asyncio.AbstractEventLoop | None = None,
    ):
        self.event_plane = event_plane
        self.subject = kv_events_subject(component_path)
        self.worker_id = worker_id
        self.loop = loop
        self.published = 0

    async def publish(self, data: KvCacheEventData) -> None:
        event = RouterEvent(worker_id=self.worker_id, data=data)
        await self.event_plane.publish(self.subject, event.to_dict())
        self.published += 1

    def engine_callback(self) -> Callable[[KvEvent], None]:
        """Adapter for ``TPUEngine(kv_event_cb=...)`` — safe to call from
        the engine loop thread."""
        loop = self.loop or asyncio.get_event_loop()

        def cb(ev: KvEvent) -> None:
            data = KvCacheEventData(
                kind=ev.kind,
                block_hashes=list(ev.seq_hashes),
                parent_hash=ev.parent_hash,
            )
            try:
                asyncio.run_coroutine_threadsafe(self.publish(data), loop)
            except RuntimeError:  # loop closed during shutdown
                logger.debug("dropping kv event after loop close")

        return cb


class KvMetricsPublisher:
    """Holds the latest ForwardPassMetrics; use ``stats_handler`` when
    serving an endpoint so the metrics aggregator can scrape it."""

    def __init__(self):
        self.current = ForwardPassMetrics()

    def update(self, metrics: ForwardPassMetrics | dict) -> None:
        if isinstance(metrics, dict):
            metrics = ForwardPassMetrics.from_dict(metrics)
        self.current = metrics

    def stats_handler(self) -> dict:
        return self.current.to_dict()
