"""Fleet observability plane (docs/observability.md "Fleet plane").

Three pieces turn per-instance telemetry into one fleet view:

- :class:`TransferLedger` — every disagg KV lease transfer is recorded
  per (src_instance, dst_instance) link with its payload size and
  extract→ack duration, maintaining an online EWMA bandwidth estimate
  per link. This is the exact input surface the topology-aware
  disaggregation item needs (NetKV: decode-instance selection driven by
  *measured* transfer cost): ``estimate_transfer_s`` answers "what
  would shipping N bytes over this link cost right now".
- :class:`FleetAggregator` / :class:`FleetView` — scrape every
  instance's stats-plane ``metrics()`` snapshot (or ``/metrics``
  Prometheus text) into one rollup, *tolerant of dead or garbage
  members*: a scrape failure tags the member in ``missing`` and is
  excluded from the rollup — it never raises and never poisons the
  healthy members' numbers. Config skew (differing ``build_info``
  fingerprints) is surfaced per scrape.
- :func:`render_top` — the ``llmctl top`` dashboard body: per-instance
  occupancy / queue depth / shed+preempt counters, per-link MB/s, and
  skew/missing warnings, as plain text so it renders identically in a
  terminal refresh loop and a test assertion.

The same :meth:`FleetView.from_snapshots` builds the simulator's fleet
rollup (``SimReport.fleet``), so fleet numbers are comparable live↔sim
by construction.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass, field

from .fingerprint import DRIFT_ALERT_THRESHOLD

# EWMA weight for the per-link bandwidth estimate: new observations move
# the estimate by this fraction, so a link's number settles within a
# handful of transfers but one straggler doesn't erase the history.
BW_EWMA_ALPHA = 0.3

# Cold-start bandwidth prior for never-observed links (bytes/s):
# ~100 MB/s, well under any healthy host-bounce TCP link, so an
# unmeasured path is priced pessimistically until real transfers teach
# the ledger otherwise. Override per deployment with DYN_KV_DEFAULT_BW_BPS.
DEFAULT_LINK_BANDWIDTH_BPS = 100e6


def _env_bw(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default

# Tolerant key aliases: engine ``metrics()`` snapshots and parsed
# ``/metrics`` Prometheus text spell the same quantity differently.
_FIELD_ALIASES = {
    "running": ("num_requests_running", "dynamo_engine_num_requests_running",
                "request_active_slots"),
    "waiting": ("num_requests_waiting", "dynamo_engine_num_requests_waiting"),
    "occupancy": ("gpu_cache_usage_perc", "hbm_page_occupancy",
                  "dynamo_engine_hbm_page_occupancy"),
    "active_slots": ("request_active_slots",),
    "total_slots": ("request_total_slots",),
    "preemptions": ("preemptions", "dynamo_preemptions_total"),
    "shed": ("requests_shed", "dynamo_requests_shed_total"),
    "ledger_violations": ("kv_ledger_violations",
                          "dynamo_kv_ledger_violations_total"),
    # G2 host-tier occupancy (docs/engine_perf.md "Predictive KV
    # tiering"): fleet views show host-tier pressure per instance.
    "host_pages": ("kv_host_pages", "host_cache_resident",
                   "dynamo_kv_host_pages"),
    # Workload drift (docs/observability.md "Workload fingerprint"):
    # live-vs-pinned fingerprint distance per instance.
    "workload_drift": ("workload_drift_score",
                       "dynamo_workload_drift_score"),
}


@dataclass
class LinkStats:
    """One directed (src, dst) link's ledger entry."""

    src: str
    dst: str
    transfers: int = 0
    bytes: int = 0
    duration_s: float = 0.0
    bandwidth_bps: float = 0.0  # EWMA of bytes / extract->ack duration

    def to_dict(self) -> dict:
        return {
            "src": self.src,
            "dst": self.dst,
            "transfers": self.transfers,
            "bytes": self.bytes,
            "duration_s": round(self.duration_s, 6),
            "bandwidth_bps": round(self.bandwidth_bps, 1),
        }


class TransferLedger:
    """Per-link KV transfer accounting with online bandwidth estimates.

    Thread-safe: ``record`` runs on the asyncio transfer paths while
    scrapes read from serving threads — every access to ``_links`` sits
    under ``_lock`` (see the dynlint lock manifest). Pure host ints and
    floats; nothing here ever touches a device value.
    """

    def __init__(self, default_bandwidth_bps: float | None = None):
        self._lock = threading.Lock()
        self._links: dict[tuple[str, str], LinkStats] = {}
        # Cold-start prior: `estimate_transfer_s` on a never-observed
        # link answers with this bandwidth instead of None, so reclaim
        # triage and the decode selector get a finite cost on a fresh
        # fleet (first transfer hasn't landed yet). Deliberately
        # conservative — a modest host-bounce TCP figure — so cold links
        # look *expensive* until measured, never free.
        if default_bandwidth_bps is None:
            default_bandwidth_bps = _env_bw(
                "DYN_KV_DEFAULT_BW_BPS", DEFAULT_LINK_BANDWIDTH_BPS
            )
        self.default_bandwidth_bps = float(default_bandwidth_bps)

    def record(
        self, src: str, dst: str, n_bytes: int, duration_s: float
    ) -> None:
        """One observed lease transfer: ``n_bytes`` moved src→dst in
        ``duration_s`` (extract→ack). Degenerate observations (empty,
        instantaneous) still count the transfer but leave the bandwidth
        estimate alone."""
        src, dst = src or "?", dst or "?"
        with self._lock:
            link = self._links.get((src, dst))
            if link is None:
                link = self._links[(src, dst)] = LinkStats(src, dst)
            link.transfers += 1
            link.bytes += int(n_bytes)
            link.duration_s += max(float(duration_s), 0.0)
            if n_bytes > 0 and duration_s > 0:
                obs = n_bytes / duration_s
                link.bandwidth_bps = (
                    obs
                    if link.bandwidth_bps <= 0
                    else (1 - BW_EWMA_ALPHA) * link.bandwidth_bps
                    + BW_EWMA_ALPHA * obs
                )
        # Prometheus mirrors ride the process hub (never raise into the
        # transfer path — the ledger must work under a bare registry).
        try:
            from .spans import get_telemetry

            tel = get_telemetry()
            tel.kv_link_transfers.labels(src, dst).inc()
            tel.kv_link_bytes.labels(src, dst).inc(max(int(n_bytes), 0))
            if n_bytes > 0 and duration_s > 0:
                tel.kv_link_bandwidth.labels(src, dst).set(
                    self.bandwidth_bps(src, dst) or 0.0
                )
        except Exception:  # noqa: BLE001 - telemetry must not break transfers
            pass

    def bandwidth_bps(self, src: str, dst: str) -> float | None:
        """The link's current EWMA estimate (None = never observed)."""
        with self._lock:
            link = self._links.get((src or "?", dst or "?"))
            if link is None or link.bandwidth_bps <= 0:
                return None
            return link.bandwidth_bps

    def estimate_transfer_s(
        self, src: str, dst: str, n_bytes: int
    ) -> float | None:
        """Predicted wall time to move ``n_bytes`` over the link — the
        number the topology-aware decode selector and reclaim triage
        fold into their scores. A never-observed link answers at
        ``default_bandwidth_bps`` (cold-start prior) instead of None, so
        a fresh fleet's first triage never divides by zero; None only
        when the prior itself is disabled (<= 0)."""
        bw = self.bandwidth_bps(src, dst)
        if bw is None:
            bw = self.default_bandwidth_bps
        if bw <= 0:
            return None
        return n_bytes / bw

    def snapshot(self) -> list[dict]:
        """Deterministically ordered link stats (src, dst sorted) — the
        ``kv_links`` metrics() key FleetAggregator rolls up."""
        with self._lock:
            links = [self._links[k].to_dict() for k in sorted(self._links)]
        return links

    def reset(self) -> None:
        with self._lock:
            self._links.clear()


_ledger: TransferLedger | None = None
_ledger_lock = threading.Lock()


def get_transfer_ledger() -> TransferLedger:
    """The process-wide ledger (one per instance, like the telemetry
    hub)."""
    global _ledger
    if _ledger is None:
        with _ledger_lock:
            if _ledger is None:
                _ledger = TransferLedger()
    return _ledger


# --------------------------------------------------------------- fleet view
# Label block: quoted values may contain '}' and escaped quotes, so the
# body is "runs of non-quote-non-} chars or whole quoted strings".
_LABELS_RE = re.compile(r'\{((?:[^"}]|"(?:[^"\\]|\\.)*")*)\}')
_LABEL_PAIR_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> dict[str, object]:
    """Collapse Prometheus exposition text into {metric_name: value},
    summing across label sets (enough for fleet rollups: totals and
    gauges; histograms contribute their _sum/_count series). The
    optional trailing exposition timestamp is discarded, never mistaken
    for the value. The ``dynamo_build_info`` sample is special-cased:
    its fingerprint lives entirely in its labels, so they are returned
    as a ``build_info`` dict for the skew detector.

    Well-formed text goes through prometheus_client's own parser
    (correct escaping/timestamps); text that parser rejects — a member
    returning garbage is exactly the fleet plane's fault-tolerance case
    — falls back to a lenient line-by-line parse that skips the bad
    lines instead of discarding the whole payload."""
    try:
        from prometheus_client.parser import text_string_to_metric_families

        out: dict[str, object] = {}
        for family in text_string_to_metric_families(text):
            for sample in family.samples:
                if sample.name == "dynamo_build_info":
                    out["build_info"] = dict(sample.labels)
                    continue
                out[sample.name] = (
                    float(out.get(sample.name, 0.0) or 0.0)
                    + float(sample.value)
                )
        return out
    except Exception:  # noqa: BLE001 - malformed payload: lenient fallback
        return _parse_prometheus_lenient(text)


def _parse_prometheus_lenient(text: str) -> dict[str, object]:
    out: dict[str, object] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            labels_m = _LABELS_RE.search(line)
            bare = _LABELS_RE.sub(" ", line, count=1)
            parts = bare.split()
            if len(parts) < 2:
                continue
            name = parts[0]
            if not name.isidentifier():
                continue
            value = float(parts[1])  # parts[2], if present, is the ts
            if name == "dynamo_build_info" and labels_m:
                out["build_info"] = {
                    k: v for k, v in _LABEL_PAIR_RE.findall(labels_m.group(1))
                }
                continue
            out[name] = float(out.get(name, 0.0) or 0.0) + value
        except ValueError:
            continue
    return out


def _pick(d: dict, aliases: tuple[str, ...], default=0.0) -> float:
    for key in aliases:
        if key in d:
            try:
                return float(d[key])
            except (TypeError, ValueError):
                return default
    return default


@dataclass
class InstanceView:
    """One member's normalized slice of the fleet view."""

    name: str
    running: int = 0
    waiting: int = 0
    occupancy: float = 0.0
    active_slots: int = 0
    total_slots: int = 0
    preemptions: int = 0
    shed: int = 0
    ledger_violations: int = 0
    host_pages: int = 0
    workload_drift: float = 0.0
    draining: bool = False
    build_info: dict = field(default_factory=dict)
    links: list[dict] = field(default_factory=list)

    @classmethod
    def from_metrics(cls, name: str, m: dict) -> "InstanceView":
        """Tolerant extraction from an engine ``metrics()`` snapshot or
        a :func:`parse_prometheus_text` dict — unknown keys ignored,
        missing keys default, non-numeric garbage treated as missing."""
        view = cls(name=name)
        view.running = int(_pick(m, _FIELD_ALIASES["running"]))
        view.waiting = int(_pick(m, _FIELD_ALIASES["waiting"]))
        view.occupancy = _pick(m, _FIELD_ALIASES["occupancy"])
        view.active_slots = int(_pick(m, _FIELD_ALIASES["active_slots"]))
        view.total_slots = int(_pick(m, _FIELD_ALIASES["total_slots"]))
        view.preemptions = int(_pick(m, _FIELD_ALIASES["preemptions"]))
        view.shed = int(_pick(m, _FIELD_ALIASES["shed"]))
        view.ledger_violations = int(
            _pick(m, _FIELD_ALIASES["ledger_violations"])
        )
        view.host_pages = int(_pick(m, _FIELD_ALIASES["host_pages"]))
        view.workload_drift = round(
            float(_pick(m, _FIELD_ALIASES["workload_drift"])), 4
        )
        view.draining = bool(m.get("draining", False))
        bi = m.get("build_info")
        if isinstance(bi, dict):
            view.build_info = bi
        links = m.get("kv_links")
        if isinstance(links, list):
            view.links = [lk for lk in links if isinstance(lk, dict)]
        return view

    def fingerprint(self) -> str:
        """Comparable config identity for skew detection."""
        bi = self.build_info
        return (
            f"{bi.get('manifest_hash', '')}|{bi.get('jax_version', '')}"
            f"|{bi.get('prefix_sharing', '')}|{bi.get('spec', '')}"
        )


@dataclass
class FleetView:
    """One scrape's fleet state: healthy members, tagged missing
    members, and the deterministic rollup both the dashboard and the
    simulator report print."""

    members: dict[str, InstanceView] = field(default_factory=dict)
    missing: dict[str, str] = field(default_factory=dict)  # name -> reason
    scraped_at: float = 0.0

    @classmethod
    def from_snapshots(cls, snapshots: dict[str, object]) -> "FleetView":
        """Build a view from raw per-instance snapshots. A value that is
        not a dict (an exception a scraper caught, garbage bytes, None —
        a dead or lying member) tags the member as missing instead of
        raising; the healthy members still roll up."""
        view = cls(scraped_at=time.time())
        for name in sorted(snapshots, key=str):
            raw = snapshots[name]
            if isinstance(raw, dict):
                try:
                    view.members[str(name)] = InstanceView.from_metrics(
                        str(name), raw
                    )
                except Exception as e:  # noqa: BLE001 - tag, don't poison
                    view.missing[str(name)] = f"unparseable: {e}"
            elif isinstance(raw, BaseException):
                view.missing[str(name)] = f"{type(raw).__name__}: {raw}"
            else:
                view.missing[str(name)] = (
                    f"garbage snapshot ({type(raw).__name__})"
                )
        return view

    def config_skew(self) -> list[str]:
        """Instances whose build fingerprint differs from the fleet's
        most common one (empty = no skew / single instance). Members
        with no build_info at all (an exporter that predates the gauge,
        or a scrape surface that dropped it) are *unknown*, not skewed —
        flagging them would turn every mixed-surface fleet red."""
        prints: dict[str, list[str]] = {}
        for name, m in self.members.items():
            if not m.build_info:
                continue
            prints.setdefault(m.fingerprint(), []).append(name)
        if len(prints) <= 1:
            return []
        majority = max(prints.values(), key=len)
        return sorted(
            name
            for fp, names in prints.items()
            for name in names
            if names is not majority
        )

    def merged_links(self) -> list[dict]:
        """Per-link rollup across members: bytes/transfers summed,
        bandwidth duration-weighted (deterministic order)."""
        acc: dict[tuple[str, str], dict] = {}
        for m in self.members.values():
            for link in m.links:
                key = (str(link.get("src", "?")), str(link.get("dst", "?")))
                slot = acc.setdefault(
                    key,
                    {"src": key[0], "dst": key[1], "transfers": 0,
                     "bytes": 0, "duration_s": 0.0, "bandwidth_bps": 0.0},
                )
                slot["transfers"] += int(link.get("transfers", 0) or 0)
                slot["bytes"] += int(link.get("bytes", 0) or 0)
                slot["duration_s"] += float(link.get("duration_s", 0) or 0)
        for slot in acc.values():
            if slot["duration_s"] > 0:
                slot["bandwidth_bps"] = round(
                    slot["bytes"] / slot["duration_s"], 1
                )
            slot["duration_s"] = round(slot["duration_s"], 6)
        return [acc[k] for k in sorted(acc)]

    def rollup(self) -> dict:
        """The fleet headline numbers (deterministically ordered; the
        same dict shape lands in ``SimReport.fleet``)."""
        members = list(self.members.values())
        n = len(members)
        occ = (
            sum(m.occupancy for m in members) / n if n else 0.0
        )
        return {
            "instances": n,
            "missing": sorted(self.missing),
            "running": sum(m.running for m in members),
            "waiting": sum(m.waiting for m in members),
            "occupancy_mean": round(occ, 4),
            "preemptions": sum(m.preemptions for m in members),
            "shed": sum(m.shed for m in members),
            "ledger_violations": sum(m.ledger_violations for m in members),
            "host_pages": sum(m.host_pages for m in members),
            # Max (not mean): one drifted instance is the actionable
            # signal — averaging would dilute it across a large fleet.
            "workload_drift": round(
                max((m.workload_drift for m in members), default=0.0), 4
            ),
            "config_skew": self.config_skew(),
            "links": self.merged_links(),
        }


class FleetAggregator:
    """Scrape a set of per-instance sources into one :class:`FleetView`.

    ``sources`` maps instance name → a zero-arg callable returning that
    instance's metrics dict (sync or async). Any source that raises,
    times out upstream, or returns garbage tags its member as missing —
    one bad instance can never break the fleet view. For a live
    cluster, :meth:`scrape_runtime` walks the discovery plane instead.
    """

    def __init__(
        self, sources: dict | None = None, timeout_s: float | None = 5.0
    ):
        self.sources = dict(sources or {})
        self.timeout_s = timeout_s

    async def scrape(self) -> FleetView:
        import asyncio
        import inspect

        async def one(src) -> object:
            # Bounded per member: an instance that accepted the scrape
            # and then wedged (died mid-scrape) must tag itself
            # missing, not hang the whole dashboard. Members scrape
            # concurrently, so the whole pass is bounded by ONE
            # timeout_s regardless of how many are wedged.
            try:
                raw = src()
                if inspect.isawaitable(raw):
                    raw = (
                        await asyncio.wait_for(raw, self.timeout_s)
                        if self.timeout_s
                        else await raw
                    )
                return raw
            except Exception as e:  # noqa: BLE001 - dead member, tagged
                return e

        names = list(self.sources)
        results = await asyncio.gather(
            *[one(self.sources[n]) for n in names]
        )
        return FleetView.from_snapshots(dict(zip(names, results)))

    @staticmethod
    async def scrape_runtime(drt, timeout_s: float = 5.0) -> FleetView:
        """Fleet view over every instance on a live discovery plane
        (``llmctl top``): per-instance stats-plane scrapes, draining
        flags from discovery metadata. Each scrape is bounded by
        ``timeout_s`` — a member dying *mid*-scrape (accepted the
        connection, never answered) times out and is tagged missing
        like any other failure, instead of hanging the dashboard."""
        import asyncio

        try:
            instances = await drt.discovery.list_instances("")
        except Exception as e:  # noqa: BLE001 - no discovery = empty fleet
            view = FleetView(scraped_at=time.time())
            view.missing["discovery"] = f"{type(e).__name__}: {e}"
            return view

        async def one(info) -> object:
            try:
                stats = await asyncio.wait_for(
                    drt.request_plane.scrape_stats(info), timeout_s
                )
                if isinstance(stats, dict):
                    stats = dict(stats)
                    stats.setdefault(
                        "draining",
                        bool((info.metadata or {}).get("draining")),
                    )
                return stats
            except Exception as e:  # noqa: BLE001 - dead member, tagged
                return e

        names = [
            f"{getattr(getattr(i, 'address', None), 'component', '?')}"
            f"/{i.instance_id}"
            for i in instances
        ]
        # Concurrent member scrapes: wedged members cost ONE timeout_s
        # for the whole pass, not one each.
        results = await asyncio.gather(*[one(i) for i in instances])
        return FleetView.from_snapshots(dict(zip(names, results)))


def render_top(view: FleetView) -> str:
    """The ``llmctl top`` dashboard body (plain text, deterministic)."""
    roll = view.rollup()
    lines = [
        f"fleet: {roll['instances']} instance(s)"
        + (f", {len(roll['missing'])} missing" if roll["missing"] else "")
        + f" — running {roll['running']}, waiting {roll['waiting']}, "
        f"occupancy {roll['occupancy_mean']:.0%}, host pages "
        f"{roll['host_pages']}, shed {roll['shed']}, "
        f"preempt {roll['preemptions']}, ledger violations "
        f"{roll['ledger_violations']}, workload drift "
        f"{roll['workload_drift']:.2f}"
    ]
    if view.members:
        name_w = max(len(n) for n in view.members)
        lines.append(
            f"{'instance':<{name_w}}  run wait  occ%  slots  host  shed  "
            f"preempt  flags"
        )
        for name in sorted(view.members):
            m = view.members[name]
            flags = []
            if m.draining:
                flags.append("draining")
            if m.ledger_violations:
                flags.append(f"LEDGER!{m.ledger_violations}")
            if name in roll["config_skew"]:
                flags.append("SKEW")
            if m.workload_drift >= DRIFT_ALERT_THRESHOLD:
                flags.append(f"DRIFT:{m.workload_drift:.2f}")
            lines.append(
                f"{name:<{name_w}}  {m.running:3d} {m.waiting:4d}  "
                f"{m.occupancy:4.0%}  {m.active_slots}/{m.total_slots}"
                f"  {m.host_pages:4d}  {m.shed:4d}  {m.preemptions:7d}  "
                f"{','.join(flags) or '-'}"
            )
    for name in sorted(view.missing):
        lines.append(f"{name}  MISSING ({view.missing[name]})")
    if roll["links"]:
        lines.append("links (src -> dst):")
        for link in roll["links"]:
            mbps = link["bandwidth_bps"] / (1 << 20)
            lines.append(
                f"  {link['src']} -> {link['dst']}: "
                f"{link['transfers']} transfers, "
                f"{link['bytes'] / (1 << 20):.2f} MB, {mbps:.1f} MB/s"
            )
    if roll["config_skew"]:
        lines.append(
            "CONFIG SKEW: " + ", ".join(roll["config_skew"])
            + " differ from the fleet majority build"
        )
    return "\n".join(lines)
