"""Deploy tier: artifact build/round-trip, K8s rendering, api-store REST.

Reference capability anchors: ``deploy/dynamo/cli/bentos.py`` (build),
``deploy/dynamo/api-store/ai_dynamo_store/api/`` (registry),
``deploy/dynamo/operator/`` (per-component Deployment/Service
rendering, here generated statically for GKE TPU node pools).
"""

import json
import os

import aiohttp
import pytest
import yaml

from dynamo_exp_tpu.deploy import (
    build_artifact,
    read_manifest,
    render_graph_manifests,
    to_yaml,
)
from dynamo_exp_tpu.deploy.api_store import ApiStore
from dynamo_exp_tpu.deploy.cli import main as deploy_cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GRAPH = "examples.llm.graphs.agg:Frontend"
CONFIG = os.path.join(REPO, "examples/llm/configs/agg.yaml")


@pytest.fixture
def artifact(tmp_path):
    out = str(tmp_path / "agg.tar.gz")
    manifest = build_artifact(
        GRAPH, out, config_path=CONFIG, src_root=REPO, packages=["examples"]
    )
    return out, manifest


def test_build_artifact_manifest(artifact):
    path, manifest = artifact
    names = [s.name for s in manifest.services]
    # dependencies-first: the worker precedes the frontend.
    assert "Frontend" in names and "TpuWorker" in names
    assert names.index("TpuWorker") < names.index("Frontend")
    front = next(s for s in manifest.services if s.name == "Frontend")
    assert front.depends_on  # graph edges captured
    assert manifest.version and len(manifest.version) == 16
    assert "TpuWorker" in manifest.config_yaml

    again = read_manifest(path)
    assert again.version == manifest.version
    assert [s.name for s in again.services] == names


def test_build_is_content_addressed(tmp_path):
    a = build_artifact(GRAPH, str(tmp_path / "a.tar.gz"), src_root=REPO,
                       packages=["examples"])
    b = build_artifact(GRAPH, str(tmp_path / "b.tar.gz"), src_root=REPO,
                       packages=["examples"])
    assert a.version == b.version  # same source -> same version


def test_render_k8s_manifests(artifact):
    _, manifest = artifact
    docs = render_graph_manifests(manifest, image="img:1", deployment="d1")
    kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
    assert ("Deployment", "d1-coordinator") in kinds
    assert ("Service", "d1-coordinator") in kinds
    assert ("ConfigMap", "d1-config") in kinds
    assert ("Service", "d1-http") in kinds

    worker = next(
        d for d in docs
        if d["kind"] == "Deployment" and d["metadata"]["name"] == "d1-tpuworker"
    )
    pod = worker["spec"]["template"]["spec"]
    c = pod["containers"][0]
    # TPU chips render as google.com/tpu limits + GKE node selectors.
    assert c["resources"]["limits"]["google.com/tpu"]
    assert "cloud.google.com/gke-tpu-accelerator" in pod["nodeSelector"]
    # Every component points at the deployment's coordinator.
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["DYN_COORDINATOR"] == "d1-coordinator:6650"
    assert "--service-name" in c["command"]
    # The YAML bundle parses back into the same number of documents.
    assert len(list(yaml.safe_load_all(to_yaml(docs)))) == len(docs)


def test_render_multihost_slices(artifact):
    _, manifest = artifact
    worker = next(s for s in manifest.services if s.name == "TpuWorker")
    worker.resources = {"tpu": 4, "tpu_hosts": 2}
    docs = render_graph_manifests(manifest, image="img:1", deployment="mh")
    ranks = [
        d for d in docs
        if d["kind"] == "Deployment"
        and d["metadata"]["name"].startswith("mh-tpuworker-")
    ]
    assert len(ranks) == 2
    cmd0 = ranks[0]["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--num-nodes" in cmd0 and "--node-rank" in cmd0
    assert "--deployment" in cmd0  # leader-key namespacing wired through


async def test_api_store_artifact_and_deployment_lifecycle(tmp_path, artifact):
    path, manifest = artifact
    store = ApiStore(str(tmp_path / "store"))
    addr = await store.start()
    try:
        async with aiohttp.ClientSession() as s:
            with open(path, "rb") as f:
                async with s.post(f"{addr}/api/v1/artifacts", data=f.read()) as r:
                    assert r.status == 200
                    up = await r.json()
            assert up == {"name": manifest.name, "version": manifest.version}

            async with s.get(f"{addr}/api/v1/artifacts") as r:
                listing = await r.json()
            assert [a["name"] for a in listing] == [manifest.name]

            # Deploy: renders manifests server-side and records them.
            async with s.post(
                f"{addr}/api/v1/deployments",
                json={"artifact": manifest.name, "version": manifest.version,
                      "image": "img:2", "name": "prod"},
            ) as r:
                assert r.status == 200
            async with s.get(f"{addr}/api/v1/deployments/prod") as r:
                rec = await r.json()
            assert rec["image"] == "img:2"
            docs = list(yaml.safe_load_all(rec["manifests_yaml"]))
            assert any(d["metadata"]["name"] == "prod-coordinator" for d in docs)

            # Download round-trips the tarball byte-exactly enough to
            # re-read the manifest.
            async with s.get(
                f"{addr}/api/v1/artifacts/{manifest.name}/{manifest.version}"
            ) as r:
                blob = await r.read()
            dl = tmp_path / "dl.tar.gz"
            dl.write_bytes(blob)
            assert read_manifest(str(dl)).version == manifest.version

            async with s.delete(f"{addr}/api/v1/deployments/prod") as r:
                assert r.status == 200
            async with s.get(f"{addr}/api/v1/deployments/prod") as r:
                assert r.status == 404

            # Garbage upload is rejected, not stored.
            async with s.post(f"{addr}/api/v1/artifacts", data=b"junk") as r:
                assert r.status == 400
    finally:
        await store.close()


def test_deploy_cli_build_and_render(tmp_path, capsys):
    out = str(tmp_path / "cli.tar.gz")
    rc = deploy_cli([
        "build", GRAPH, "-o", out, "-f", CONFIG,
        "--src-root", REPO, "--packages", "examples",
    ])
    assert rc == 0
    built = json.loads(capsys.readouterr().out)
    assert built["services"]

    rc = deploy_cli(["render", out, "--image", "x:y", "--deployment", "dd"])
    assert rc == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    assert any(d["metadata"]["name"] == "dd-coordinator" for d in docs)


# ------------------------------------------------------------- operator
async def test_operator_reconciles_applies_and_finalizes(tmp_path, artifact):
    """The reconcile loop end-to-end against the in-memory cluster:
    create → applied + Ready status; drift → re-applied; replica
    override → patched; record deleted → resources finalized
    (reference: dynamographdeployment_controller.go Reconcile)."""
    from dynamo_exp_tpu.deploy.operator import (
        DeploymentOperator,
        MemoryBackend,
        _doc_key,
    )

    path, manifest = artifact
    store = ApiStore(str(tmp_path / "store"))
    addr = await store.start()
    backend = MemoryBackend()
    op = DeploymentOperator(str(tmp_path / "store"), backend, interval_s=0.05)
    try:
        async with aiohttp.ClientSession() as s:
            with open(path, "rb") as f:
                r = await s.post(f"{addr}/api/v1/artifacts", data=f.read())
                assert r.status == 200
            r = await s.post(
                f"{addr}/api/v1/deployments",
                json={
                    "name": "prod",
                    "artifact": manifest.name,
                    "version": manifest.version,
                },
            )
            assert r.status == 200

        # 1. First pass: everything applied, status written, Ready.
        results = await op.reconcile_all()
        assert results["prod"].phase == "Ready"
        assert results["prod"].applied > 0
        applied = backend.applied["prod"]
        assert any(k[0] == "Deployment" for k in applied)
        assert any(k[0] == "Service" for k in applied)
        rec = json.load(open(tmp_path / "store/deployments/prod.json"))
        assert rec["status"]["phase"] == "Ready"
        assert all(rec["status"]["services_ready"].values())

        # 2. Steady state: a second pass applies nothing (hash match).
        results = await op.reconcile_all()
        assert results["prod"].applied == 0 and results["prod"].deleted == 0

        # 3. Drift: mutate one applied doc; reconcile restores it.
        key = next(k for k in applied if k[0] == "Deployment")
        backend.applied["prod"][key] = {"kind": "Deployment",
                                        "metadata": {"name": key[1]},
                                        "tampered": True}
        results = await op.reconcile_all()
        assert results["prod"].applied == 1
        assert "tampered" not in backend.applied["prod"][key]

        # 4. Spec change: replica override patches the rendered doc.
        rec = json.load(open(tmp_path / "store/deployments/prod.json"))
        svc = next(k[1] for k in applied if k[0] == "Deployment")
        short = svc.split("-")[-1]
        rec["services_spec"] = {short: {"replicas": 3}}
        json.dump(rec, open(tmp_path / "store/deployments/prod.json", "w"))
        await op.reconcile_all()
        assert backend.applied["prod"][(
            "Deployment", svc)]["spec"]["replicas"] == 3

        # 5. Unreadiness propagates: mark one deployment unready.
        backend.ready_keys.discard(("prod", key))
        results = await op.reconcile_all()
        assert results["prod"].phase == "Deploying"
        backend.ready_keys.add(("prod", key))

        # 6. Record deleted → finalizer removes every owned resource.
        async with aiohttp.ClientSession() as s:
            r = await s.delete(f"{addr}/api/v1/deployments/prod")
            assert r.status == 200
        await op.reconcile_all()
        assert backend.applied.get("prod", {}) == {}
    finally:
        await op.close()
        await store.close()


async def test_kubectl_backend_second_reconcile_applies_nothing(tmp_path):
    """Drift detection at the KubectlBackend level: the content-hash
    annotation must hash the doc AS RENDERED (before _decorate adds
    ownership labels), or every reconcile pass sees a mismatch and
    re-applies the whole graph forever."""
    from dynamo_exp_tpu.deploy.operator import (
        DeploymentOperator,
        KubectlBackend,
    )

    class FakeKubectlBackend(KubectlBackend):
        """kubectl simulated in memory: apply/get/delete semantics, same
        label/annotation round-trip a real apiserver performs."""

        def __init__(self):
            super().__init__()
            self.cluster: dict[tuple[str, str], dict] = {}
            self.apply_count = 0

        async def _run(self, *args, stdin=None):
            if args[0] == "apply":
                doc = yaml.safe_load(stdin)
                self.cluster[(doc["kind"], doc["metadata"]["name"])] = doc
                self.apply_count += 1
                return ""
            if args[0] == "get" and "-l" in args:
                kind = args[1]
                items = [
                    d for (k, _), d in self.cluster.items()
                    if k.lower() == kind
                ]
                return json.dumps({"items": items})
            if args[0] == "get":
                kind, name = args[1], args[2]
                doc = self.cluster[(kind.capitalize(), name)]
                avail = doc.get("spec", {}).get("replicas", 1)
                return json.dumps(
                    {**doc, "status": {"availableReplicas": avail}}
                )
            if args[0] == "delete":
                self.cluster.pop((args[1].capitalize(), args[2]), None)
                return ""
            raise AssertionError(f"unexpected kubectl args: {args}")

    docs = [
        {
            "kind": "Deployment",
            "apiVersion": "apps/v1",
            "metadata": {
                "name": "d1-app",
                "labels": {"app.kubernetes.io/name": "d1-app"},
            },
            "spec": {"replicas": 1},
        },
        {
            "kind": "Service",
            "apiVersion": "v1",
            "metadata": {"name": "d1-app"},
            "spec": {"ports": [{"port": 80}]},
        },
    ]
    ddir = tmp_path / "store" / "deployments"
    os.makedirs(ddir)
    with open(ddir / "d1.json", "w") as f:
        json.dump({"name": "d1", "manifests_yaml": yaml.safe_dump_all(docs)}, f)

    backend = FakeKubectlBackend()
    op = DeploymentOperator(str(tmp_path / "store"), backend, interval_s=0.05)
    results = await op.reconcile_all()
    assert results["d1"].phase == "Ready"
    assert results["d1"].applied == 2
    assert backend.apply_count == 2
    # Owned resources carry the labels + content-hash annotation.
    dep = backend.cluster[("Deployment", "d1-app")]
    assert dep["metadata"]["labels"]["dynamo-exp-tpu/deployment"] == "d1"
    assert backend.HASH_ANNOTATION in dep["metadata"]["annotations"]

    # Steady state: the second pass must apply 0 resources.
    results = await op.reconcile_all()
    assert results["d1"].applied == 0 and results["d1"].deleted == 0
    assert backend.apply_count == 2


def test_helm_chart_assets_parse():
    """Chart.yaml/values.yaml are valid YAML and templates reference
    only values that exist (cheap lint — helm itself isn't in CI)."""
    import re

    base = os.path.join(REPO, "deploy/helm/dynamo-exp-tpu")
    chart = yaml.safe_load(open(os.path.join(base, "Chart.yaml")))
    assert chart["name"] == "dynamo-exp-tpu"
    values = yaml.safe_load(open(os.path.join(base, "values.yaml")))
    assert values["coordinator"]["enabled"] is True

    tdir = os.path.join(base, "templates")
    refs = set()
    for fn in os.listdir(tdir):
        text = open(os.path.join(tdir, fn)).read()
        refs.update(re.findall(r"\.Values\.([a-zA-Z0-9_.]+)", text))
    for ref in refs:
        node = values
        for part in ref.split("."):
            assert isinstance(node, dict) and part in node, (
                f"template references undefined value .Values.{ref}"
            )
            node = node[part]
