"""Minimal 3-stage SDK pipeline: Frontend -> Middle -> Backend.

Reference parity: ``/root/reference/examples/hello_world/hello_world.py``
(:28-75) — no accelerator, pure control-plane plumbing. Each stage
decorates the text and streams it on. Serve with:

    python -m dynamo_exp_tpu.sdk.serve \
        examples.hello_world.hello_world:Frontend --start-coordinator
"""

from dynamo_exp_tpu.sdk import depends, endpoint, service


@service(dynamo={"namespace": "hello"})
class Backend:
    """Generates tokens from the (twice-decorated) request text."""

    @endpoint()
    async def generate(self, request: dict):
        text = request.get("text", "")
        for word in f"{text}-back".split(","):
            yield {"token": word}


@service(dynamo={"namespace": "hello"})
class Middle:
    backend = depends(Backend)

    @endpoint()
    async def generate(self, request: dict):
        text = request.get("text", "")
        stream = await self.backend.generate({"text": f"{text}-mid"})
        async for item in stream:
            yield item


@service(dynamo={"namespace": "hello"})
class Frontend:
    middle = depends(Middle)

    # Configurable via ServiceConfig YAML ({"Frontend": {"greeting": ...}}).
    greeting = "hello"

    @endpoint()
    async def generate(self, request: dict):
        text = f"{self.greeting},{request.get('text', '')}"
        stream = await self.middle.generate({"text": text})
        async for item in stream:
            yield item
