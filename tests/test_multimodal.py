"""Multimodal seam tests: soft-token forward + the encode worker graph.

Reference capability anchor: ``examples/multimodal/components/
encode_worker.py:21-60`` (separate encode worker streaming image
features into the LLM's input sequence).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_exp_tpu.models import TINY, forward, init_kv_cache, init_params


def test_forward_token_embeds_matches_id_lookup():
    """Soft tokens that equal the embedding rows must reproduce the
    id-based forward exactly — pins the token_embeds seam."""
    cfg = dataclasses.replace(TINY, dtype="float32")
    params = init_params(jax.random.PRNGKey(1), cfg)
    toks = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
    pos = jnp.arange(8, dtype=jnp.int32)[None]
    table = jnp.asarray([[1]], jnp.int32)

    def run(**kw):
        k, v = init_kv_cache(cfg, num_pages=4, page_size=8, dtype=jnp.float32)
        out, _, _ = forward(params, cfg, toks, pos, table, k, v, **kw)
        return np.asarray(out)

    embeds = jnp.take(params["embed"], toks, axis=0)
    np.testing.assert_allclose(
        run(token_embeds=embeds), run(), atol=1e-6
    )


def test_vision_encoder_shapes():
    """Tower + projector emit [num_patches, lm_hidden] soft tokens
    (padded/cropped to the tower raster)."""
    from examples.multimodal.components.encode_worker import VisionEncoder

    enc = VisionEncoder(lm_hidden_size=64, image_size=16, patch=8)
    img = np.random.RandomState(0).rand(32, 24, 3)
    out = enc(img)
    assert out.shape == (4, 64)  # (16/8)^2 patches → LM hidden


def test_vision_forward_matches_hf_clip(tmp_path):
    """A tiny random-but-real CLIPVisionModel checkpoint round-trips:
    save with transformers, load with our safetensors loader, compare
    last_hidden_state (reference: encode_worker.py:21-60 runs the HF
    tower; we must produce the same features)."""
    import torch
    from transformers import CLIPVisionConfig, CLIPVisionModel

    from dynamo_exp_tpu.models.vision import load_vision_params, vision_forward

    hf_cfg = CLIPVisionConfig(
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        image_size=32,
        patch_size=8,
    )
    torch.manual_seed(0)
    model = CLIPVisionModel(hf_cfg).eval()
    d = str(tmp_path / "clip")
    model.save_pretrained(d, safe_serialization=True)

    params, cfg = load_vision_params(d)
    img = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    ours = np.asarray(vision_forward(params, cfg, img))
    with torch.no_grad():
        theirs = model(
            pixel_values=torch.from_numpy(img.transpose(0, 3, 1, 2))
        ).last_hidden_state.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-5)


def test_encode_worker_loads_real_checkpoint(tmp_path):
    """EncodeWorker with model_path: HF tower weights + attached
    projector produce LM-hidden soft tokens."""
    import torch
    from transformers import CLIPVisionConfig, CLIPVisionModel

    from examples.multimodal.components.encode_worker import VisionEncoder

    hf_cfg = CLIPVisionConfig(
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        image_size=16,
        patch_size=8,
    )
    torch.manual_seed(1)
    d = str(tmp_path / "clip")
    CLIPVisionModel(hf_cfg).save_pretrained(d, safe_serialization=True)

    enc = VisionEncoder(lm_hidden_size=64, model_path=d)
    out = enc(np.random.RandomState(2).rand(16, 16, 3))
    assert out.shape == (4, 64)
    assert np.isfinite(out).all()


async def test_encode_worker_to_vision_chat_flow():
    """The demo graph end-to-end in-process: encode → soft-token prefill
    → a sampled token."""
    from examples.multimodal.components.encode_worker import EncodeWorker
    from examples.multimodal.multimodal_demo import VisionChat

    enc = EncodeWorker()
    enc.lm_hidden_size = 64
    enc.image_size = 16
    enc.patch = 8
    await enc.build()

    chat = VisionChat()
    await chat.build()

    # Wire the dependency by hand (no supervisor in this test).
    class _Dep:
        async def generate(self, request):
            async def gen():
                async for item in enc.encode(request):
                    yield item

            return gen()

    VisionChat.encoder._client = _Dep()
    img = np.random.RandomState(1).rand(16, 16, 3)
    results = []
    async for item in chat.generate(
        {"pixels": img.tolist(), "token_ids": [5, 7, 9]}
    ):
        results.append(item)
    VisionChat.encoder._client = None
    assert results
    assert results[0]["n_image_tokens"] == 4  # 16/8 x 16/8
    assert 0 <= results[0]["next_token"] < TINY.vocab_size


def test_vision_feature_layer_matches_hf_hidden_states(tmp_path):
    """LLaVA's vision_feature_layer=-2 selects the penultimate layer;
    our scan-collected per-layer outputs must match HF hidden_states."""
    import dataclasses

    import torch
    from transformers import CLIPVisionConfig, CLIPVisionModel

    from dynamo_exp_tpu.models.vision import load_vision_params, vision_forward

    hf_cfg = CLIPVisionConfig(
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=3,
        num_attention_heads=4,
        image_size=16,
        patch_size=8,
    )
    torch.manual_seed(2)
    model = CLIPVisionModel(hf_cfg).eval()
    d = str(tmp_path / "clip")
    model.save_pretrained(d, safe_serialization=True)

    params, cfg = load_vision_params(d)
    cfg = dataclasses.replace(cfg, feature_layer=-2)
    img = np.random.RandomState(1).rand(1, 16, 16, 3).astype(np.float32)
    ours = np.asarray(vision_forward(params, cfg, img))
    with torch.no_grad():
        hs = model(
            pixel_values=torch.from_numpy(img.transpose(0, 3, 1, 2)),
            output_hidden_states=True,
        ).hidden_states
    np.testing.assert_allclose(ours, hs[-2].numpy(), atol=2e-5)
