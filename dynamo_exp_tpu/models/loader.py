"""Load Llama-family HF checkpoints (safetensors) into stacked JAX params.

The reference leaves weight loading to the wrapped engines (and its own
GGUF loader, ``/root/reference/lib/llm/src/gguf.rs``). Here checkpoints
are read tensor-by-tensor from safetensors, transposed to the matmul
layout ``x @ W`` used by ``models/llama.py``, and stacked along a leading
layer axis for the scan.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from .config import ModelConfig
from .llama import Params, _dtype


def _open_safetensors(path: str):
    from safetensors import safe_open  # lazy: only needed for real ckpts

    files = sorted(
        os.path.join(path, f)
        for f in os.listdir(path)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors under {path}")
    handles = [safe_open(f, framework="numpy") for f in files]
    index: dict[str, int] = {}
    for i, h in enumerate(handles):
        for name in h.keys():
            index[name] = i
    return handles, index


SUPPORTED_MODEL_TYPES = (
    "llama", "mistral", "qwen2", "qwen3", "gemma", "gemma2",
    "gemma3_text", "phi3",
    "mixtral", "qwen2_moe", "qwen3_moe",
)


def load_params(path: str, cfg: ModelConfig | None = None) -> tuple[Params, ModelConfig]:
    """Load a HF checkpoint directory (llama/mistral/qwen2/mixtral
    families) into the stacked param pytree."""
    if cfg is None:
        cfg = ModelConfig.from_pretrained(path)
    if cfg.model_type not in SUPPORTED_MODEL_TYPES:
        # Fail loudly: e.g. qwen2_moe parses to an MoE config but uses
        # different tensor names (mlp.experts.N.gate_proj + shared
        # expert) — loading it with mixtral names would KeyError deep in
        # the loop with no hint the arch is unsupported.
        raise ValueError(
            f"unsupported model_type {cfg.model_type!r}; "
            f"supported: {SUPPORTED_MODEL_TYPES}"
        )
    handles, index = _open_safetensors(path)
    dt = _dtype(cfg)

    def get(name: str) -> np.ndarray:
        arr = handles[index[name]].get_tensor(name)
        if arr.dtype == np.dtype("V2"):  # raw bf16 comes out as void16
            arr = arr.view(np.uint16)
            arr = (arr.astype(np.uint32) << 16).view(np.float32)
        return arr

    def linear(name: str) -> np.ndarray:
        # HF stores [out, in]; we use x @ W so transpose to [in, out].
        return get(name).T

    pre = "model."
    L = cfg.num_layers
    keys = ["attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
            "w_gate", "w_up", "w_down"]
    if cfg.attention_bias:
        keys += ["bq", "bk", "bv"]
    if cfg.qk_norm:
        keys += ["q_norm", "k_norm"]
    if cfg.post_norms:
        keys += ["post_attn_norm", "post_ffn_norm"]
    if cfg.is_moe:
        keys.append("router")
        if cfg.shared_expert_intermediate_size:
            keys += ["shared_gate", "shared_up", "shared_down", "shared_router"]
    layers: dict[str, list] = {k: [] for k in keys}
    for i in range(L):
        p = f"{pre}layers.{i}."
        layers["attn_norm"].append(get(p + "input_layernorm.weight"))
        if cfg.model_type == "phi3":
            # phi3 packs q/k/v into one tensor [(H + 2*Hkv)*hd, D].
            qkv = get(p + "self_attn.qkv_proj.weight")
            hd = cfg.head_dim_
            nq = cfg.num_heads * hd
            nk = cfg.num_kv_heads * hd
            layers["wq"].append(qkv[:nq].T)
            layers["wk"].append(qkv[nq : nq + nk].T)
            layers["wv"].append(qkv[nq + nk :].T)
        else:
            layers["wq"].append(linear(p + "self_attn.q_proj.weight"))
            layers["wk"].append(linear(p + "self_attn.k_proj.weight"))
            layers["wv"].append(linear(p + "self_attn.v_proj.weight"))
        layers["wo"].append(linear(p + "self_attn.o_proj.weight"))
        if cfg.post_norms:
            # gemma2 layer norms: post_attention_layernorm norms the
            # attn OUTPUT; pre_feedforward_layernorm is the pre-FFN
            # norm (the role post_attention_layernorm plays elsewhere).
            layers["post_attn_norm"].append(
                get(p + "post_attention_layernorm.weight")
            )
            layers["mlp_norm"].append(
                get(p + "pre_feedforward_layernorm.weight")
            )
            layers["post_ffn_norm"].append(
                get(p + "post_feedforward_layernorm.weight")
            )
        else:
            layers["mlp_norm"].append(
                get(p + "post_attention_layernorm.weight")
            )
        if cfg.attention_bias:  # qwen2: bias on q/k/v only
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
        if cfg.qk_norm:  # qwen3: [head_dim] norms applied per head
            layers["q_norm"].append(get(p + "self_attn.q_norm.weight"))
            layers["k_norm"].append(get(p + "self_attn.k_norm.weight"))
        if cfg.is_moe:
            # Stack per-expert weights to [E, D, I] / [E, I, D] for the
            # grouped ragged_dot matmuls. Mixtral names them
            # block_sparse_moe.experts.N.{w1=gate, w3=up, w2=down};
            # qwen3_moe uses mlp.experts.N.{gate,up,down}_proj.
            if cfg.model_type in ("qwen2_moe", "qwen3_moe"):
                m = p + "mlp."
                names = ("gate_proj.weight", "up_proj.weight", "down_proj.weight")
            else:
                m = p + "block_sparse_moe."
                names = ("w1.weight", "w3.weight", "w2.weight")
            layers["router"].append(linear(m + "gate.weight"))
            for key, tname in zip(("w_gate", "w_up", "w_down"), names):
                layers[key].append(np.stack([
                    linear(f"{m}experts.{e}.{tname}")
                    for e in range(cfg.num_experts)
                ]))
            if cfg.shared_expert_intermediate_size:  # qwen2_moe
                s = p + "mlp.shared_expert."
                layers["shared_gate"].append(linear(s + "gate_proj.weight"))
                layers["shared_up"].append(linear(s + "up_proj.weight"))
                layers["shared_down"].append(linear(s + "down_proj.weight"))
                # shared_expert_gate is Linear(D, 1): [1, D] -> [D]
                layers["shared_router"].append(
                    get(p + "mlp.shared_expert_gate.weight")[0]
                )
        elif cfg.model_type == "phi3":
            # phi3 packs gate and up into one tensor [2I, D].
            gu = get(p + "mlp.gate_up_proj.weight")
            layers["w_gate"].append(gu[: cfg.intermediate_size].T)
            layers["w_up"].append(gu[cfg.intermediate_size :].T)
            layers["w_down"].append(linear(p + "mlp.down_proj.weight"))
        else:
            layers["w_gate"].append(linear(p + "mlp.gate_proj.weight"))
            layers["w_up"].append(linear(p + "mlp.up_proj.weight"))
            layers["w_down"].append(linear(p + "mlp.down_proj.weight"))

    params: Params = {
        "embed": jnp.asarray(get(pre + "embed_tokens.weight"), dt),
        "layers": {
            k: jnp.asarray(np.stack(v), dt) for k, v in layers.items()
        },
        "final_norm": jnp.asarray(get(pre + "norm.weight"), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(linear("lm_head.weight"), dt)
    handles.clear()  # drop mmap handles now rather than at caller GC
    return params, cfg
