"""Distributed runtime and the Namespace -> Component -> Endpoint hierarchy.

Capability parity with the reference component model
(``/root/reference/lib/runtime/src/component.rs:120-192`` and
``distributed.rs:31-186``): a ``DistributedRuntime`` owns the transports;
namespaces contain components; components expose named endpoints that are
served over the request plane and registered in discovery under a lease,
so that worker death removes the instance automatically.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import AsyncIterator

from .client import Client
from .config import RuntimeConfig
from .engine import AsyncEngineContext
from .runtime import Runtime
from .transports.base import (
    Discovery,
    EndpointAddress,
    EventPlane,
    Handler,
    InstanceInfo,
    Lease,
    ObjectStore,
    RequestPlane,
    ServedEndpoint,
    StatsHandler,
    WorkQueue,
)
from .transports.inproc import InProcDiscovery, InProcRequestPlane

logger = logging.getLogger(__name__)

# KV prefix where drain intent is published: ``llmctl drain <instance>``
# writes ``{DRAIN_PREFIX}{instance_id}``; the serving process watches the
# prefix and initiates its own graceful drain (the worker owns its lease,
# so the operator plane never has to forge registrations).
DRAIN_PREFIX = "v1/drain/"

# KV prefix where spot-reclamation notices land: ``llmctl reclaim
# <instance> --grace-s N`` writes ``{RECLAIM_PREFIX}{instance_id}`` with
# a JSON ``{"grace_s": N}`` payload. Same watch discipline as drain,
# but the value carries the platform's grace window so the instance's
# ReclaimController can run deadline-bounded triage under it
# (docs/fault_tolerance.md "Spot reclamation & live migration").
RECLAIM_PREFIX = "v1/reclaim/"

# Default grace window when a reclaim notice carries none (SIGTERM,
# malformed payload): seconds-scale, matching typical spot preemption.
DEFAULT_RECLAIM_GRACE_S = 30.0

# Endpoints served under one lease, for composing unique instance ids.
# Per-lease (not process-global): a long-lived process serving many
# endpoints across many leases must never overflow one lease's id range
# into another's. The counter lives on the lease object so its state
# dies with the lease (no global table to leak across lease churn).
_ENDPOINTS_PER_LEASE = 10_000


def _next_endpoint_seq(lease) -> int:
    seq = getattr(lease, "_endpoint_seq", 0) + 1
    if seq >= _ENDPOINTS_PER_LEASE:
        raise RuntimeError(
            f"lease {lease.lease_id} exceeded {_ENDPOINTS_PER_LEASE} endpoints"
        )
    lease._endpoint_seq = seq
    return seq


class DistributedRuntime:
    """Runtime + cluster transports. In static mode (no coordinator
    configured) discovery and the request plane are in-process."""

    def __init__(
        self,
        runtime: Runtime | None = None,
        config: RuntimeConfig | None = None,
        discovery: Discovery | None = None,
        request_plane: RequestPlane | None = None,
        event_plane: "EventPlane | None" = None,
    ):
        self.config = config or RuntimeConfig()
        self.runtime = runtime or Runtime(
            num_blocking_threads=self.config.num_blocking_threads
        )
        if discovery is None or request_plane is None:
            if self.config.is_static:
                discovery = discovery or InProcDiscovery()
                request_plane = request_plane or InProcRequestPlane()
            else:
                from .transports.coordinator import CoordinatorDiscovery
                from .transports.tcp import TcpRequestPlane

                discovery = discovery or CoordinatorDiscovery(
                    self.config.coordinator_endpoint,
                    lease_ttl_s=self.config.lease_ttl_s,
                )
                request_plane = request_plane or TcpRequestPlane(
                    bind_host=self.config.response_host,
                    bind_port=self.config.response_port,
                )
        self.discovery = discovery
        self.request_plane = request_plane
        # The discovery backend is the factory for its sibling planes, so
        # events/queues/blobs automatically ride the same fabric.
        self.event_plane = event_plane or self.discovery.event_plane()
        self._namespaces: dict[str, Namespace] = {}
        self._primary_lease: Lease | None = None
        self._bg_tasks: list = []

    def spawn_background(self, coro, name: str):
        """Run a long-lived coroutine tied to this runtime's lifetime
        (heartbeats, re-publishers). Cancelled on close()."""
        import asyncio

        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._bg_tasks.append(task)
        task.add_done_callback(
            lambda t: self._bg_tasks.remove(t) if t in self._bg_tasks else None
        )
        return task

    @classmethod
    def from_settings(cls, config_path: str | None = None) -> "DistributedRuntime":
        return cls(config=RuntimeConfig.from_settings(config_path))

    @classmethod
    def detached(cls) -> "DistributedRuntime":
        """Static single-process runtime (no discovery services)."""
        return cls(config=RuntimeConfig())

    async def primary_lease(self) -> Lease:
        if self._primary_lease is None or not self._primary_lease.is_valid():
            self._primary_lease = await self.discovery.create_lease(
                self.config.lease_ttl_s
            )
        return self._primary_lease

    def namespace(self, name: str) -> "Namespace":
        if name not in self._namespaces:
            self._namespaces[name] = Namespace(self, name)
        return self._namespaces[name]

    def work_queue(self, name: str) -> "WorkQueue":
        """A named FIFO work queue (JetStream work-queue equivalent)."""
        return self.discovery.work_queue(name)

    @property
    def object_store(self) -> "ObjectStore":
        """Bucketed blob store (NATS object-store equivalent, holds MDCs)."""
        return self.discovery.object_store()

    def shutdown(self) -> None:
        self.runtime.shutdown()

    async def close(self) -> None:
        import asyncio

        for task in list(self._bg_tasks):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                # Swallow only the bg task's own cancellation; if close()
                # itself was cancelled (run.py bounds it with wait_for),
                # that must propagate or the shutdown cap is defeated.
                if not task.cancelled():
                    raise
            except Exception:  # noqa: BLE001 - a failing bg task must
                pass  # not block runtime teardown
        self._bg_tasks.clear()
        if self._primary_lease is not None and self._primary_lease.is_valid():
            await self._primary_lease.revoke()
        await self.request_plane.close()
        await self.event_plane.close()
        await self.discovery.close()
        await self.runtime.close()


class Namespace:
    def __init__(self, drt: DistributedRuntime, name: str):
        _validate_segment(name)
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)


class Component:
    """A discoverable unit of work (e.g. "worker", "router", "prefill")."""

    def __init__(self, namespace: Namespace, name: str):
        _validate_segment(name)
        self.namespace = namespace
        self.name = name

    @property
    def drt(self) -> DistributedRuntime:
        return self.namespace.drt

    @property
    def path(self) -> str:
        return f"{self.namespace.name}/components/{self.name}"

    @property
    def service_name(self) -> str:
        return f"{self.namespace.name}_{self.name}"

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    async def scrape_stats(self, include_draining: bool = True) -> dict[int, dict]:
        """Collect live stats from every instance of this component.

        ``include_draining=False`` drops instances that advertised drain
        in their discovery metadata — selection planes (the KV router's
        metrics aggregator) must not schedule onto them.
        """
        from .health import is_draining

        out: dict[int, dict] = {}
        for info in await self.drt.discovery.list_instances(self.path):
            if not include_draining and is_draining(info):
                continue
            try:
                out[info.instance_id] = await self.drt.request_plane.scrape_stats(info)
            except ConnectionError:
                continue
        return out


class Endpoint:
    def __init__(self, component: Component, name: str):
        _validate_segment(name)
        self.component = component
        self.name = name

    @property
    def drt(self) -> DistributedRuntime:
        return self.component.drt

    @property
    def address(self) -> EndpointAddress:
        return EndpointAddress(
            self.component.namespace.name, self.component.name, self.name
        )

    @property
    def path(self) -> str:
        return self.address.path

    async def serve_endpoint(
        self,
        handler: Handler,
        stats_handler: StatsHandler | None = None,
        lease: Lease | None = None,
        metadata: dict | None = None,
    ) -> "ServedInstance":
        """Register + serve this endpoint; returns the live instance handle."""
        drt = self.drt
        if lease is None:
            lease = await drt.primary_lease()
        # Instance ids must be unique ACROSS processes (the registry and
        # direct routing key on them), so derive them from the lease id —
        # globally unique per coordinator — plus a per-process counter
        # for the several endpoints one process serves under one primary
        # lease. A bare per-process counter would make every worker
        # process claim instance 1 and clobber its peers in discovery.
        info = InstanceInfo(
            address=self.address,
            instance_id=lease.lease_id * _ENDPOINTS_PER_LEASE
            + _next_endpoint_seq(lease),
            metadata=metadata or {},
        )
        served = await drt.request_plane.serve(info, handler, stats_handler)
        await drt.discovery.register_instance(info, lease)
        logger.info("serving endpoint %s as instance %d", self.path, info.instance_id)
        instance = ServedInstance(self, info, served, lease)
        instance._start_drain_watch()
        instance._start_reclaim_watch()
        return instance

    async def client(
        self,
        static_instances: list[InstanceInfo] | None = None,
        health=None,
    ) -> Client:
        """A client that tracks this endpoint's live instances. ``health``
        overrides the default HealthTracker (custom breaker thresholds,
        injectable clock under test)."""
        if static_instances is not None:
            return Client.new_static(
                self.drt.request_plane, static_instances, health=health
            )
        return await Client.new_dynamic(
            self.drt.runtime,
            self.drt.discovery,
            self.drt.request_plane,
            self.path,
            health=health,
        )


class ServedInstance:
    def __init__(
        self,
        endpoint: Endpoint,
        info: InstanceInfo,
        served: ServedEndpoint,
        lease: Lease,
    ):
        self.endpoint = endpoint
        self.info = info
        self._served = served
        self.lease = lease
        self._drain_task = None
        self._reclaim_task = None
        # Reclaim hook: ``async def on_reclaim(grace_s: float)`` —
        # typically ReclaimController.run (runtime/reclaim.py). Invoked
        # once, after the ``reclaiming`` metadata republish, inside the
        # grace window. None = metadata-only reclaim (routers stop
        # sending; in-flight streams ride the journal failover path).
        self.on_reclaim = None

    @property
    def instance_id(self) -> int:
        return self.info.instance_id

    @property
    def is_draining(self) -> bool:
        from .health import is_draining

        return is_draining(self.info)

    @property
    def is_reclaiming(self) -> bool:
        from .health import is_reclaiming

        return is_reclaiming(self.info)

    def _start_drain_watch(self) -> None:
        """Watch the drain-intent KV prefix so ``llmctl drain <id>`` can
        trigger a graceful drain without owning this worker's lease."""
        drt = self.endpoint.drt

        async def _watch() -> None:
            key = f"{DRAIN_PREFIX}{self.info.instance_id}"
            try:
                async for snapshot in drt.discovery.kv_watch_prefix(DRAIN_PREFIX):
                    if key in snapshot:
                        await self.drain()
                        # Consume the intent: the key has done its job,
                        # and leaving it would grow the drain prefix
                        # forever (and re-ship stale keys to every
                        # instance's watcher on each KV change).
                        with contextlib.suppress(Exception):
                            await drt.discovery.kv_delete(key)
                        return
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - a broken control-plane watch
                # must not kill serving; drain stays operator-reachable
                # via ServedInstance.drain() in-process.
                logger.debug(
                    "drain watch for instance %d ended",
                    self.info.instance_id,
                    exc_info=True,
                )

        self._drain_task = drt.spawn_background(
            _watch(), name=f"drain-watch-{self.info.instance_id}"
        )

    def _start_reclaim_watch(self) -> None:
        """Watch the reclaim-notice KV prefix so ``llmctl reclaim <id>
        --grace-s N`` (or a platform agent writing the same key) can
        trigger deadline-bounded reclaim without owning this worker's
        lease. The value carries the grace window as JSON."""
        import json

        drt = self.endpoint.drt

        async def _watch() -> None:
            key = f"{RECLAIM_PREFIX}{self.info.instance_id}"
            try:
                async for snapshot in drt.discovery.kv_watch_prefix(
                    RECLAIM_PREFIX
                ):
                    if key not in snapshot:
                        continue
                    grace_s = DEFAULT_RECLAIM_GRACE_S
                    with contextlib.suppress(Exception):
                        raw = snapshot[key]
                        if isinstance(raw, (bytes, bytearray)):
                            raw = raw.decode()
                        grace_s = float(json.loads(raw).get("grace_s", grace_s))
                    await self.reclaim(grace_s)
                    # Consume the notice (same hygiene as the drain key).
                    with contextlib.suppress(Exception):
                        await drt.discovery.kv_delete(key)
                    return
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - a broken control-plane watch
                # must not kill serving; reclaim stays reachable via
                # ServedInstance.reclaim() in-process (SIGTERM path).
                logger.debug(
                    "reclaim watch for instance %d ended",
                    self.info.instance_id,
                    exc_info=True,
                )

        self._reclaim_task = drt.spawn_background(
            _watch(), name=f"reclaim-watch-{self.info.instance_id}"
        )

    async def reclaim(self, grace_s: float = DEFAULT_RECLAIM_GRACE_S) -> None:
        """Spot-reclamation notice: republish this instance with
        ``reclaiming`` (and ``draining``, so every legacy gate holds) in
        its discovery metadata — routers and the KV aggregator stop
        sending work within one watch event — then hand the grace
        window to :attr:`on_reclaim` for in-flight triage
        (docs/fault_tolerance.md "Spot reclamation & live migration")."""
        if self.info.metadata.get("reclaiming"):
            return
        from ..telemetry import get_telemetry

        self.info.metadata = {
            **self.info.metadata,
            "reclaiming": True,
            "reclaim_grace_s": grace_s,
            "draining": True,
        }
        await self.endpoint.drt.discovery.register_instance(self.info, self.lease)
        get_telemetry().reclaim_events.labels("notice").inc()
        logger.warning(
            "instance %d reclaiming (endpoint %s, grace %.1fs)",
            self.info.instance_id,
            self.endpoint.path,
            grace_s,
        )
        if self.on_reclaim is not None:
            await self.on_reclaim(grace_s)

    async def drain(self) -> None:
        """Signal drain: republish this instance with ``draining`` set in
        its discovery metadata. Routers stop sending new work on their
        next snapshot; in-flight requests keep streaming. Call
        :meth:`close` afterwards to wait them out and deregister."""
        if self.info.metadata.get("draining"):
            return
        from ..telemetry import get_telemetry

        self.info.metadata = {**self.info.metadata, "draining": True}
        await self.endpoint.drt.discovery.register_instance(self.info, self.lease)
        get_telemetry().drain_events.labels("started").inc()
        logger.info(
            "instance %d draining (endpoint %s)",
            self.info.instance_id,
            self.endpoint.path,
        )

    async def close(self, revoke_lease: bool | None = None) -> None:
        """Stop serving: drop from discovery first, then drain inflight
        requests — the reference's graceful-shutdown order.

        By default the lease is revoked only if it is dedicated to this
        instance; a process-shared primary lease (which other endpoints
        ride on) is left alone and just this instance is deregistered.
        """
        drt = self.endpoint.drt
        if self._drain_task is not None:
            self._drain_task.cancel()
            self._drain_task = None
        if self._reclaim_task is not None:
            self._reclaim_task.cancel()
            self._reclaim_task = None
        if revoke_lease is None:
            revoke_lease = self.lease is not drt._primary_lease
        if revoke_lease and self.lease.is_valid():
            await self.lease.revoke()
        else:
            await drt.discovery.deregister_instance(self.info.instance_id)
        await self._served.close()
        if self.is_draining:
            from ..telemetry import get_telemetry

            get_telemetry().drain_events.labels("completed").inc()


def _validate_segment(name: str) -> None:
    if not name or any(c in name for c in "./ \t\n"):
        raise ValueError(f"invalid name segment: {name!r}")


async def annotated_stream(
    engine,
    request: dict,
    context: AsyncEngineContext | None = None,
) -> AsyncIterator[dict]:
    """Adapt an AsyncEngine of dicts into an Annotated-frame handler stream."""
    from .annotated import Annotated

    ctx = context or AsyncEngineContext()
    try:
        stream = await engine.generate(request, ctx)
        async for item in stream:
            yield Annotated.from_data(item).to_dict()
    except Exception as e:  # error frames travel in-band
        yield Annotated.from_error(str(e)).to_dict()
