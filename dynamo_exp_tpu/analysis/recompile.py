"""recompile-hazard checker: variant-cache keys must be bucketed.

The engine's compiled-program caches (`_ragged_fns`, the jit-internal
gather/scatter shape cache) key variants
by static shapes. The whole lattice stays O(log) *only* because every
shape-carrying key component passes through a bucket helper
(``ragged_tokens_bucket_for``, ``ragged_page_bucket_for``,
``page_move_bucket_for``, …). One raw dynamic int in a key position —
``self._ragged_fn(len(part), …)`` — compiles a fresh program per
distinct value under real load: a recompile storm the steady-state
guard test only catches for the shapes it happens to drive.

A ``VariantSiteManifest`` names the callables whose argument positions
become cache keys. An argument is accepted when it traces (through
per-function dataflow) to:

- a call to any ``*bucket_for`` helper,
- an int constant, or ``min``/``max`` over accepted values,
- static config (an attribute path containing ``cfg``),
- ``np.full(bucket, …)`` / ``jnp.asarray(bucketed)`` of an accepted
  value (the padded index-vector idiom of the page movers).

Anything else is flagged; deliberate carries (a chained window reusing
the dispatched window's already-bucketed row count) get an inline
``# dynlint: recompile-hazard(reason)``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Finding, attr_chain, dataflow_units, own_nodes

RULE = "recompile-hazard"

_BUCKET_SUFFIX = "bucket_for"


@dataclass(frozen=True)
class VariantSiteManifest:
    path: str
    # callee name (Name or self.<name>) -> shape-carrying arg positions
    sites: dict[str, tuple[int, ...]]


def _callee_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
    ):
        return func.attr
    return None


class _BucketFlow:
    """Per-function, per-line classification of names holding bucketed
    values.

    Bindings are processed in source order and each one records whether
    the name was bucketed *after* it — so a rebind to anything not
    provably bucketed KILLS the name from that point on (`rows =
    bucket_for(...)` then `rows = len(part)` can't launder the raw
    int), and a bucketed rebind *after* a raw use can't retroactively
    whitewash the earlier dispatch (use sites consult the last binding
    at or before their own line)."""

    def __init__(self, fn: ast.AST):
        # name -> [(bind line, bucketed after this bind)], line-ordered.
        self._history: dict[str, list[tuple[int, bool]]] = {}
        binds: list[tuple[int, int, str, ast.AST | None]] = []
        for node in own_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    binds.append(
                        (node.lineno, node.col_offset, t.id, node.value)
                    )
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(node.target, ast.Name):
                    binds.append(
                        (node.lineno, node.col_offset, node.target.id, None)
                    )
            elif isinstance(node, ast.For) and isinstance(
                node.target, ast.Name
            ):
                binds.append(
                    (node.lineno, node.col_offset, node.target.id, None)
                )
        for line, _, name, value in sorted(binds, key=lambda b: b[:2]):
            bucketed = value is not None and self.ok(value, line)
            self._history.setdefault(name, []).append((line, bucketed))

    def ok(self, node: ast.AST, line: int) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, (int, bool))
        if isinstance(node, ast.Name):
            state = False
            for bind_line, bucketed in self._history.get(node.id, ()):
                if bind_line > line:
                    break
                state = bucketed
            return state
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            return "cfg" in chain[:-1] if chain else False
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1].endswith(_BUCKET_SUFFIX):
                return True
            if chain and chain[-1] in ("min", "max") and node.args:
                return all(self.ok(a, line) for a in node.args)
            # np.full(bucket, ...) / jnp.asarray(bucketed): the padded
            # page-id vector whose static length IS the bucket.
            if chain and chain[0] in ("np", "numpy") and chain[-1] == "full":
                return bool(node.args) and self.ok(node.args[0], line)
            if chain and chain[0] in ("jnp", "jax") and chain[-1] in (
                "asarray",
                "array",
            ):
                return bool(node.args) and self.ok(node.args[0], line)
        return False


class RecompileHazardChecker:
    rule = RULE

    def __init__(
        self, manifests: tuple[VariantSiteManifest, ...] | None = None
    ):
        if manifests is None:
            from .zones import VARIANT_SITE_MANIFESTS

            manifests = VARIANT_SITE_MANIFESTS
        self.manifests = manifests

    def check(
        self, rel_path: str, tree: ast.Module, source: str
    ) -> list[Finding]:
        sites: dict[str, tuple[int, ...]] = {}
        for m in self.manifests:
            if m.path == rel_path:
                sites.update(m.sites)
        if not sites:
            return []
        findings: list[Finding] = []
        for fn in dataflow_units(tree):
            flow = _BucketFlow(fn)
            for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = _callee_name(node.func)
                if callee not in sites:
                    continue
                # Don't flag the builder's own recursive mentions (the
                # def itself is matched by name, not the call).
                suspect: list[tuple[str, ast.AST]] = []
                for pos in sites[callee]:
                    if pos < len(node.args):
                        suspect.append((f"arg {pos}", node.args[pos]))
                # Keyword spellings can't be mapped to key positions
                # without the signature, so EVERY keyword value must be
                # bucket-derived (the builders are internal and called
                # positionally by convention; a keyword call site that
                # trips this either gets the positional spelling or a
                # reviewed waiver).
                for kw in node.keywords:
                    if kw.arg is not None:
                        suspect.append((f"keyword {kw.arg!r}", kw.value))
                for label, value in suspect:
                    if not flow.ok(value, node.lineno):
                        arg_src = ast.unparse(value)
                        findings.append(
                            Finding(
                                rule=RULE,
                                file=rel_path,
                                line=node.lineno,
                                col=node.col_offset,
                                end_line=node.end_lineno or node.lineno,
                                message=(
                                    f"compiled-variant key {label} of "
                                    f"{callee}(...) is not bucket-derived: "
                                    f"{arg_src!r} — route it through a "
                                    f"*_bucket_for helper"
                                ),
                            )
                        )
        return findings

    def check_source(self, rel_path: str, source: str) -> list[Finding]:
        return self.check(rel_path, ast.parse(source), source)
