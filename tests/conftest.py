"""Shared fixtures for the test suite."""

import pytest

from .fixtures import build_tiny_model_dir


@pytest.fixture(scope="session")
def tiny_model_dir(tmp_path_factory) -> str:
    return build_tiny_model_dir(str(tmp_path_factory.mktemp("tiny-model")))


@pytest.fixture(autouse=True)
def _kv_ledger_guard(request):
    """KV conservation auditor as a suite-wide invariant
    (docs/observability.md "KV conservation auditor"): every in-process
    engine's in-loop check and stop()-time audit append violations to a
    process-wide registry; this guard asserts the registry did not grow
    during the test — so the chaos/overload/prefix-sharing/resumable
    state machines are conservation-checked continuously, not just by
    the dedicated ledger suite. Tests that inject a leak on purpose
    mark themselves ``ledger_leak`` (the guard then expects growth and
    truncates the registry for the next test)."""
    from dynamo_exp_tpu.engine.engine import LEDGER_VIOLATIONS

    before = len(LEDGER_VIOLATIONS)
    yield
    grew = LEDGER_VIOLATIONS[before:]
    if request.node.get_closest_marker("ledger_leak"):
        del LEDGER_VIOLATIONS[before:]
        assert grew, (
            "test is marked ledger_leak but the auditor saw no violation"
        )
        return
    assert not grew, (
        f"KV ledger violations during this test: {grew}"
    )
