"""Ingress model discovery: watch ``models/``, build serving chains.

Capability parity with the reference's ModelWatcher
(``/root/reference/lib/llm/src/http/service/discovery.rs:100-340``): on a
new ModelEntry, fetch the ModelDeploymentCard from the object store and
register a preprocessor→backend→router chain with the ModelManager; on
removal (lease expiry = worker death), drop the model.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging

from ..local_model import MDC_BUCKET, MODELS_PREFIX, ModelEntry
from ..model_card import ModelDeploymentCard
from ..runtime.component import DistributedRuntime
from ..runtime.push_router import RouterMode
from ..runtime.transports.base import EndpointAddress
from .service import ModelManager, build_pipeline_engine

logger = logging.getLogger(__name__)


class ModelWatcher:
    """Keeps a ModelManager in sync with the discovery KV's ``models/``."""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.RANDOM,
    ):
        self.drt = drt
        self.manager = manager
        self.router_mode = router_mode
        self._task: asyncio.Task | None = None
        # Reconciled state. Bindings map each served surface —
        # (name, "chat"/"completion") — to the serving identity it is
        # currently routed through. Chains/routers are keyed by that
        # identity — (name, endpoint, mdc_key) — NOT by name alone: one
        # name's chat and completion entries may point at different
        # workers, and each surface's traffic must ride its own entry's
        # chain.
        self._bindings: dict[tuple[str, str], tuple] = {}
        self._kv_routers: dict[tuple, object] = {}
        self._chains: dict[tuple, object] = {}

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._watch())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        for r in self._kv_routers.values():
            await r.stop()
        self._kv_routers.clear()

    async def _watch(self) -> None:
        # The watch stream itself can break (coordinator hiccup); an
        # ingress must re-establish it, not freeze its model set.
        while True:
            try:
                async for snapshot in self.drt.discovery.kv_watch_prefix(
                    MODELS_PREFIX
                ):
                    try:
                        await self._apply(snapshot)
                    except Exception:  # noqa: BLE001 - keep watching
                        logger.exception("model watch apply failed")
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - reconnect after backoff
                logger.exception("model watch stream broke; retrying")
                await asyncio.sleep(1.0)

    @staticmethod
    def _types_of(model_type: str) -> set[str]:
        return {"chat", "completion"} if model_type == "both" else {model_type}

    async def _apply(self, snapshot: dict[str, bytes]) -> None:
        """Reconcile served surfaces with the snapshot, declaratively.

        Desired state is recomputed from scratch each time: for every
        (name, type) surface, the first live entry (sorted by KV key,
        deterministic) provides the serving identity. Diffing desired
        against current bindings handles every transition in one place
        — add, last-replica removal, AND identity churn (a worker
        re-registering with a new endpoint or model card rebinds the
        surface to the new identity instead of freezing on the old).
        """
        desired: dict[tuple[str, str], tuple] = {}
        entries_by_identity: dict[tuple, ModelEntry] = {}
        for key in sorted(snapshot):
            try:
                entry = ModelEntry.from_bytes(snapshot[key])
            except Exception:  # noqa: BLE001 - one bad entry: skip it
                logger.exception("undecodable model entry %s", key)
                continue
            ident = (entry.name, entry.endpoint, entry.mdc_key)
            entries_by_identity.setdefault(ident, entry)
            for t in self._types_of(entry.model_type):
                desired.setdefault((entry.name, t), ident)

        # Bind new/changed surfaces. Per-surface guard: one bad entry
        # (missing MDC, unreadable tokenizer) must not block siblings.
        for surface, ident in desired.items():
            if self._bindings.get(surface) == ident:
                continue
            try:
                engine = self._chains.get(ident)
                if engine is None:
                    engine = await self._build_chain(entries_by_identity[ident])
                    self._chains[ident] = engine
                name, t = surface
                if t == "chat":
                    self.manager.add_chat_model(name, engine)
                else:
                    self.manager.add_completion_model(name, engine)
                self._bindings[surface] = ident
                logger.info("model %s (%s) bound to %s", name, t, ident[1])
            except Exception:  # noqa: BLE001 - retried on next KV change
                logger.exception("failed to bind %s to %s", surface, ident)

        # Unbind surfaces with no live entry left.
        for surface in [s for s in self._bindings if s not in desired]:
            name, t = surface
            if t == "chat":
                self.manager.remove_chat_model(name)
            else:
                self.manager.remove_completion_model(name)
            del self._bindings[surface]
            logger.info("model %s (%s) removed (last worker gone)", name, t)

        # Tear down chains/routers no surface routes through anymore
        # (identity died, or a rebind moved its surfaces elsewhere).
        in_use = set(self._bindings.values())
        for ck in [k for k in self._chains if k not in in_use]:
            del self._chains[ck]
        for rk in [k for k in self._kv_routers if k not in in_use]:
            router = self._kv_routers.pop(rk)
            await router.stop()  # drop its event sub + scrape loop

    async def _build_chain(self, entry: ModelEntry):
        raw = await self.drt.object_store.get(MDC_BUCKET, entry.mdc_key)
        if raw is None:
            raise RuntimeError(f"no MDC in object store for {entry.name}")
        mdc = ModelDeploymentCard.from_json(raw.decode())
        addr = EndpointAddress.from_url(entry.endpoint)
        ep = (
            self.drt.namespace(addr.namespace)
            .component(addr.component)
            .endpoint(addr.name)
        )
        from ..kv_router.router import build_routed_core

        core, kv_router = await build_routed_core(
            ep, self.router_mode, mdc.kv_cache_block_size
        )
        if kv_router is not None:
            # A retry after a partially-failed registration may rebuild
            # the chain; stop the superseded router or it scrapes forever.
            rk = (entry.name, entry.endpoint, entry.mdc_key)
            old = self._kv_routers.pop(rk, None)
            if old is not None:
                await old.stop()
            self._kv_routers[rk] = kv_router
        return build_pipeline_engine(mdc, core)
