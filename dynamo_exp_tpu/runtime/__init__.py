"""Distributed runtime: the accelerator-agnostic serving fabric."""

from .annotated import Annotated
from .client import Client, EngineError
from .component import (
    Component,
    DistributedRuntime,
    Endpoint,
    Namespace,
    ServedInstance,
    annotated_stream,
)
from .config import RuntimeConfig
from .engine import (
    AsyncEngine,
    AsyncEngineContext,
    LambdaEngine,
    ResponseStream,
)
from .logging import configure_logging
from .pipeline import (
    Context,
    MapOperator,
    Operator,
    PipelineNode,
    PipelineOperator,
    SegmentSink,
    SegmentSource,
    ServiceBackend,
    ServiceFrontend,
    build_pipeline,
    build_segment,
)
from .pool import Pool, PoolItem
from .push_router import NoInstancesError, PushRouter, RouterMode
from .runtime import CancellationToken, Runtime, Worker
from .transports.base import EndpointAddress, InstanceInfo, Lease

__all__ = [
    "Annotated",
    "AsyncEngine",
    "AsyncEngineContext",
    "CancellationToken",
    "Client",
    "Component",
    "Context",
    "DistributedRuntime",
    "Endpoint",
    "EndpointAddress",
    "EngineError",
    "InstanceInfo",
    "LambdaEngine",
    "Lease",
    "MapOperator",
    "Namespace",
    "NoInstancesError",
    "Operator",
    "PipelineNode",
    "PipelineOperator",
    "Pool",
    "PoolItem",
    "PushRouter",
    "ResponseStream",
    "RouterMode",
    "Runtime",
    "RuntimeConfig",
    "SegmentSink",
    "SegmentSource",
    "ServedInstance",
    "ServiceBackend",
    "ServiceFrontend",
    "Worker",
    "annotated_stream",
    "build_pipeline",
    "build_segment",
    "configure_logging",
]
