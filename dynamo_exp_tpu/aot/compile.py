"""AOT compilation of the lattice + persistent-cache wiring
(docs/aot.md "Compiling the lattice offline").

``aot_compile`` walks a :class:`~.lattice.CompileManifest` and runs the
SNIPPETS-grounded ahead-of-time compile step for every entry:
``jit_fn.lower(*args).compile()`` with the engine's explicit shardings
(params / KV pools are the engine's real committed arrays — ``lower``
reads their avals and shardings without executing or consuming donated
buffers). With the JAX persistent compilation cache enabled, every
compiled executable serializes to ``cache_dir`` keyed by its HLO hash —
so a *different process* (a freshly provisioned instance) that builds
the same programs deserializes them instead of recompiling, which is
the entire warm-boot story.

The lowering arguments come from :func:`~.warmup.variant_call_args` —
the same tuples ``prewarm_engine`` executes with — so the compiler, the
warmer, and the live dispatch sites cannot drift apart without the
prewarm-smoke gate catching it.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field

from .lattice import CompileManifest, build_manifest

log = logging.getLogger(__name__)

# Environment override for the persistent-cache directory; the
# ``--compile-cache-dir`` flags on run.py / llmctl aot / bench.py win.
CACHE_ENV = "DYN_COMPILE_CACHE"
MANIFEST_FILENAME = "manifest.json"


def cache_dir_from_env() -> str:
    return os.environ.get(CACHE_ENV, "").strip()


def enable_persistent_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` (created
    if missing) and drop the min-compile-time / min-entry-size gates so
    even small variants serialize. Returns False (and runs uncached)
    when this jax build doesn't support the options."""
    import jax

    if not cache_dir:
        return False
    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # unknown option on this jax version
        log.warning("persistent compilation cache unsupported; running uncached")
        return False
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except Exception:  # older jax: keep its default gate
            pass
    # The cache object is memoized at first use: a process that already
    # compiled anything (engine construction jits device_puts) latched a
    # disabled cache and would silently ignore the new directory — reset
    # so the updated config is actually read.
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:  # private seam moved: cache may already be live
        pass
    return True


def manifest_for_engine(engine, **kwargs) -> CompileManifest:
    """The full compile lattice for a live engine: its resolved
    attention implementation, its mesh shape, this process's jax."""
    import jax

    return build_manifest(
        engine.cfg,
        attn_impl=engine._attn_impl,
        mesh_shape=dict(engine.mesh.shape),
        jax_version=jax.__version__,
        interpret=engine._attn_interpret,
        **kwargs,
    )


@dataclass
class AotCompileReport:
    manifest_hash: str = ""
    compiled: int = 0
    seconds: float = 0.0
    cache_dir: str = ""
    failed: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "manifest_hash": self.manifest_hash,
            "compiled": self.compiled,
            "seconds": round(self.seconds, 3),
            "cache_dir": self.cache_dir,
            "failed": list(self.failed),
        }


def aot_compile(
    engine,
    manifest: CompileManifest | None = None,
    cache_dir: str = "",
) -> AotCompileReport:
    """AOT-lower and compile every manifest entry through the engine's
    own program builders. Pure compilation: nothing executes, no
    donated buffer is consumed, the engine's ``_ragged_fns`` cache ends
    up populated with the (still-unexecuted) jit wrappers. With
    ``cache_dir`` (or ``$DYN_COMPILE_CACHE``) set, every executable is
    also serialized for other processes; the manifest JSON is dropped
    next to the cache entries for ``llmctl aot list`` and hash checks."""
    import jax.numpy as jnp

    from .warmup import variant_call_args

    cache_dir = cache_dir or cache_dir_from_env()
    if cache_dir:
        enable_persistent_cache(cache_dir)
    if manifest is None:
        manifest = manifest_for_engine(engine)
    t0 = time.monotonic()  # dynlint: determinism(prewarm wall-clock metric)
    report = AotCompileReport(
        manifest_hash=manifest.hash(), cache_dir=cache_dir
    )
    for variant in manifest.ragged:
        fn = engine._ragged_fn_from_key(variant.key)
        try:
            fn.lower(*variant_call_args(engine, variant.key)).compile()
            report.compiled += 1
        except Exception as e:  # noqa: BLE001 - record, keep compiling
            log.exception("AOT compile failed for %s", variant)
            report.failed.append(f"{variant.key}: {e}")
    k, v = engine.k_cache, engine.v_cache
    for bucket in manifest.move_buckets:
        pids = jnp.zeros(bucket, jnp.int32)
        try:
            engine._gather_pages.lower(k, v, pids).compile()
            # The scatter's page payloads have the gather's output
            # shape: [L, bucket, page_size, HkvD] in the KV dtype.
            L = engine.cfg.model.num_layers
            hkv = (
                engine.cfg.model.num_kv_heads * engine.cfg.model.head_dim_
            )
            page = jnp.zeros(
                (L, bucket, engine.cfg.page_size, hkv),
                engine.cfg.kv_dtype_jnp,
            )
            engine._inject_pages.lower(k, v, pids, page, page).compile()
            report.compiled += 2
        except Exception as e:  # noqa: BLE001
            log.exception("AOT compile failed for move bucket %d", bucket)
            report.failed.append(f"move:{bucket}: {e}")
    try:
        zero = jnp.asarray(0, jnp.int32)
        engine._cow_pages.lower(k, v, zero, zero).compile()
        engine._init_row.lower(
            engine._counts, engine.cfg.max_decode_slots, 0
        ).compile()
        report.compiled += 2
    except Exception as e:  # noqa: BLE001
        log.exception("AOT compile failed for cow/init_row")
        report.failed.append(f"cow/init_row: {e}")
    report.seconds = time.monotonic() - t0  # dynlint: determinism(prewarm wall-clock metric)
    if cache_dir:
        write_manifest(cache_dir, manifest)
    log.info(
        "aot: compiled %d variants in %.2fs (manifest %s)%s",
        report.compiled, report.seconds, report.manifest_hash[:12],
        f", {len(report.failed)} FAILED" if report.failed else "",
    )
    return report


def write_manifest(cache_dir: str, manifest: CompileManifest) -> str:
    path = os.path.join(cache_dir, MANIFEST_FILENAME)
    with open(path, "w", encoding="utf-8") as f:
        f.write(manifest.to_json(indent=2))
        f.write("\n")
    return path


def read_manifest(cache_dir: str) -> CompileManifest | None:
    path = os.path.join(cache_dir, MANIFEST_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return CompileManifest.from_json(f.read())
