"""Subprocess worker for multi-process transport tests: serves an echo
endpoint over the coordinator + TCP planes, then blocks until killed."""

import asyncio
import sys

from dynamo_exp_tpu.runtime import Annotated, DistributedRuntime
from dynamo_exp_tpu.runtime.config import RuntimeConfig


async def echo_handler(request, context):
    for tok in request["tokens"]:
        yield Annotated.from_data({"token": tok}).to_dict()


async def main(coordinator_address: str) -> None:
    cfg = RuntimeConfig(coordinator_endpoint=coordinator_address, lease_ttl_s=2.0)
    drt = DistributedRuntime(config=cfg)
    ep = drt.namespace("mp").component("worker").endpoint("generate")
    await ep.serve_endpoint(echo_handler)
    print("worker ready", flush=True)
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main(sys.argv[1]))
