"""Radix index over page-aligned token runs (docs/prefix_sharing.md).

Each indexed *block* is one full KV page's worth of tokens, identified
by its chained sequence hash (``tokens.py``): equal sequence hashes
imply equal full prefixes, so prefix containment is a chain walk. The
index compresses linear runs — a node holds a *run* of consecutive
blocks and splits only where chains diverge (the classic radix shape) —
which keeps a fleet of thousands of same-system-prompt sequences at one
node per distinct prefix instead of one entry per page.

Beyond the flat ``hash -> payload`` map this replaces, the tree gives:

- **Partial-tail lookup** (:meth:`partial_match`): a prompt ending
  *inside* a registered block can find the block whose stored tokens
  extend its tail — the admission hook for copy-on-write page sharing.
- **Eviction-safe removal**: evicting a middle block detaches its
  descendants into an orphan set keyed by the missing parent hash;
  re-registering that block re-attaches them, so LRU eviction order
  never permanently severs a still-resident suffix.
- **Exact coverage queries** (:meth:`match_hashes`): the KV router's
  per-instance overlap scores walk the same structure the owning
  engine matches against, not an approximation.

Single-writer like its consumers (engine loop thread / indexer task /
sim event loop); no internal locking.
"""

from __future__ import annotations

from typing import Sequence


class _Node:
    """One compressed edge: a run of consecutive blocks. ``hashes[i]``
    is the chained sequence hash of the run's i-th block; ``tokens[i]``
    its token block (or None when only the hash is known, e.g. on the
    router side where events don't carry tokens)."""

    __slots__ = ("hashes", "tokens", "parent", "children", "orphan_key")

    def __init__(self, parent: "_Node | None" = None):
        self.hashes: list[int] = []
        self.tokens: list[tuple[int, ...] | None] = []
        self.parent = parent
        # first-block-hash -> child node (divergence points only).
        self.children: dict[int, "_Node"] = {}
        # The missing parent hash this node is parked under while
        # detached (None when attached) — makes unparking O(1).
        self.orphan_key: int | None = None


class PrefixIndex:
    """Radix tree over hash-chained token blocks with per-block payloads.

    ``insert``/``remove`` are O(1) amortized via a block-location map;
    ``match_hashes`` is O(matched blocks). Payloads (a device page id,
    a worker marker, a sim residency record) ride in a side map so the
    node runs stay payload-agnostic.
    """

    def __init__(self):
        self._root = _Node()
        # seq_hash -> (node, index within the node's run).
        self._loc: dict[int, tuple[_Node, int]] = {}
        self._payload: dict[int, object] = {}
        # Detached subtrees waiting for their parent block to come back
        # (evicted mid-chain): missing parent hash -> orphaned nodes.
        self._orphans: dict[int, list[_Node]] = {}

    # ---------------------------------------------------------------- stats
    @property
    def num_blocks(self) -> int:
        """All indexed blocks, including orphaned (detached) ones."""
        return len(self._loc)

    @property
    def num_orphans(self) -> int:
        """Blocks currently unreachable from the root (parent evicted)."""
        return sum(
            self._subtree_blocks(n)
            for nodes in self._orphans.values()
            for n in nodes
        )

    def _subtree_blocks(self, node: _Node) -> int:
        total = len(node.hashes)
        for child in node.children.values():
            total += self._subtree_blocks(child)
        return total

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._loc

    def payload(self, seq_hash: int):
        return self._payload.get(seq_hash)

    def set_payload(self, seq_hash: int, payload) -> None:
        if seq_hash in self._loc:
            self._payload[seq_hash] = payload

    # --------------------------------------------------------------- insert
    def insert(
        self,
        parent_hash: int | None,
        seq_hash: int,
        tokens: Sequence[int] | None = None,
        payload=None,
    ) -> bool:
        """Index one block under its parent. Returns False (refreshing
        tokens/payload in place) when the block is already present. A
        missing parent parks the block as an orphan; it attaches the
        moment the parent is (re-)inserted."""
        if seq_hash in self._loc:
            node, i = self._loc[seq_hash]
            if tokens is not None:
                node.tokens[i] = tuple(tokens)
            if payload is not None:
                self._payload[seq_hash] = payload
            return False
        tok = tuple(tokens) if tokens is not None else None
        if parent_hash is None:
            self._attach_block(self._root, len(self._root.hashes), seq_hash, tok)
        elif parent_hash in self._loc:
            pnode, pidx = self._loc[parent_hash]
            self._attach_block(pnode, pidx + 1, seq_hash, tok)
        else:
            # Orphan: a one-block node parked until the parent shows up.
            node = _Node()
            node.hashes.append(seq_hash)
            node.tokens.append(tok)
            node.orphan_key = parent_hash
            self._loc[seq_hash] = (node, 0)
            self._orphans.setdefault(parent_hash, []).append(node)
        if payload is not None:
            self._payload[seq_hash] = payload
        self._reattach_orphans(seq_hash)
        return True

    def _attach_block(
        self, node: _Node, at: int, seq_hash: int, tok: tuple[int, ...] | None
    ) -> None:
        """Place a new block as the successor of ``node.hashes[at-1]``
        (``at`` == run position the block would occupy)."""
        if at == len(node.hashes) and not node.children and node is not self._root:
            # Tail extension: the common case (a sequence registering
            # pages in order) stays one compressed run.
            node.hashes.append(seq_hash)
            node.tokens.append(tok)
            self._loc[seq_hash] = (node, at)
            return
        if at < len(node.hashes):
            self._split(node, at)  # divergence mid-run
        child = _Node(parent=node)
        child.hashes.append(seq_hash)
        child.tokens.append(tok)
        node.children[seq_hash] = child
        self._loc[seq_hash] = (child, 0)

    def _split(self, node: _Node, at: int) -> None:
        """Split ``node``'s run at ``at``: blocks [at:] move into a new
        child, making position ``at`` a branch point."""
        tail = _Node(parent=node)
        tail.hashes = node.hashes[at:]
        tail.tokens = node.tokens[at:]
        tail.children, node.children = node.children, {}
        for child in tail.children.values():
            child.parent = tail
        node.hashes = node.hashes[:at]
        node.tokens = node.tokens[:at]
        node.children[tail.hashes[0]] = tail
        for i, h in enumerate(tail.hashes):
            self._loc[h] = (tail, i)

    def _reattach_orphans(self, seq_hash: int) -> None:
        for node in self._orphans.pop(seq_hash, ()):  # children of seq_hash
            pnode, pidx = self._loc[seq_hash]
            if pidx < len(pnode.hashes) - 1:
                self._split(pnode, pidx + 1)
            node.parent = pnode
            node.orphan_key = None
            pnode.children[node.hashes[0]] = node

    # --------------------------------------------------------------- remove
    def remove(self, seq_hash: int) -> bool:
        """Drop one block (eviction). Descendants — later blocks of the
        same run and child subtrees — detach into the orphan set under
        this hash, re-attachable if the block is registered again."""
        loc = self._loc.pop(seq_hash, None)
        self._payload.pop(seq_hash, None)
        if loc is None:
            return False
        node, idx = loc
        # Everything after the removed block becomes a detached subtree
        # parented (logically) by the removed hash.
        if idx < len(node.hashes) - 1:
            self._split(node, idx + 1)
        orphan_children = list(node.children.values())
        node.children = {}
        node.hashes.pop()  # idx is now the last block
        node.tokens.pop()
        if orphan_children:
            self._orphans.setdefault(seq_hash, []).extend(orphan_children)
            for child in orphan_children:
                child.parent = None
                child.orphan_key = seq_hash
        if not node.hashes:
            if node.parent is not None:
                # Run emptied: unlink from the parent's child map.
                parent = node.parent
                for key, child in list(parent.children.items()):
                    if child is node:
                        del parent.children[key]
                        break
            elif node.orphan_key is not None:
                # A parked orphan node that empties vanishes — O(1) via
                # its recorded park key, not a scan of every bucket.
                bucket = self._orphans.get(node.orphan_key)
                if bucket is not None:
                    bucket[:] = [n for n in bucket if n is not node]
                    if not bucket:
                        del self._orphans[node.orphan_key]
        # else: the surviving run keeps its key block (idx == 0 empties
        # the node only when the run had length 1).
        return True

    # ---------------------------------------------------------------- match
    def match_hashes(self, hashes: Sequence[int]) -> list[int]:
        """Longest root-anchored run of ``hashes`` present in the index
        (page-aligned longest-prefix match). Returns the matched prefix
        of ``hashes``."""
        matched: list[int] = []
        node = self._root
        idx = len(node.hashes)  # root run is always empty
        for h in hashes:
            if idx < len(node.hashes):
                if node.hashes[idx] != h:
                    break
            else:
                child = node.children.get(h)
                if child is None:
                    break
                node, idx = child, 0
            matched.append(h)
            idx += 1
        return matched

    def coverage_blocks(self, hashes: Sequence[int]) -> int:
        return len(self.match_hashes(hashes))

    def payloads_for(self, hashes: Sequence[int]) -> list:
        return [self._payload.get(h) for h in hashes]

    def partial_match(
        self, parent_hash: int | None, tail: Sequence[int]
    ) -> tuple[int, int] | None:
        """A registered block extending ``tail``: given the last fully
        matched block (``parent_hash``; None when the query is shorter
        than one page), find a successor block whose stored tokens start
        with ``tail``. Returns (block seq_hash, covered tokens) — the
        copy-on-write partial-tail attach of docs/prefix_sharing.md —
        or None. Blocks indexed without tokens (router side) never
        partial-match."""
        if not tail:
            return None
        if parent_hash is None:
            node, idx = self._root, len(self._root.hashes) - 1
        elif parent_hash in self._loc:
            node, idx = self._loc[parent_hash]
        else:
            return None
        tail = tuple(tail)
        # Successor candidates: the next block of the same run, else the
        # first block of each child (deterministic insertion order).
        if idx + 1 < len(node.hashes):
            candidates = [(node.hashes[idx + 1], node.tokens[idx + 1])]
        else:
            candidates = [(c.hashes[0], c.tokens[0]) for c in node.children.values()]
        for h, tok in candidates:
            if tok is not None and len(tok) >= len(tail) and tok[: len(tail)] == tail:
                return h, len(tail)
        return None
