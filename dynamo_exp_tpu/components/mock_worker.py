"""Mock worker: synthetic load metrics + KV events for exercising the
metrics exporter and KV router without a TPU.

Capability parity with ``/root/reference/components/metrics/src/bin/
mock_worker.rs`` (fake ``ForwardPassMetrics`` publisher). Run standalone:

    python -m dynamo_exp_tpu.components.mock_worker \
        --coordinator HOST:PORT --component ns.comp
"""

from __future__ import annotations

import asyncio
import itertools
import random

from ..kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEventData,
    RouterEvent,
    kv_events_subject,
)
from ..runtime.component import Component, annotated_stream
from ..runtime.engine import AsyncEngineContext


class MockWorker:
    """Serves an echo endpoint whose stats drift like a loaded worker and
    publishes synthetic stored/removed KV events."""

    def __init__(self, component: Component, endpoint: str = "generate", seed: int = 0):
        self.component = component
        self.endpoint_name = endpoint
        self.rng = random.Random(seed)
        self.metrics = ForwardPassMetrics(
            request_total_slots=16, kv_total_blocks=1024
        )
        self._served = None
        self._tasks: list[asyncio.Task] = []
        self._hashes = itertools.count(1)

    async def start(self) -> int:
        from ..engines.echo import EchoEngineCore

        engine = EchoEngineCore()

        async def handler(request: dict, context: AsyncEngineContext):
            async for frame in annotated_stream(engine, request, context):
                yield frame

        ep = self.component.endpoint(self.endpoint_name)
        self._served = await ep.serve_endpoint(
            handler, stats_handler=lambda: self.metrics.to_dict()
        )
        self._tasks.append(asyncio.ensure_future(self._drift()))
        self._tasks.append(asyncio.ensure_future(self._publish_kv()))
        return self._served.instance_id

    async def _drift(self) -> None:
        while True:
            m = self.metrics
            m.request_active_slots = self.rng.randint(0, m.request_total_slots)
            m.kv_active_blocks = self.rng.randint(0, m.kv_total_blocks)
            m.num_requests_waiting = self.rng.randint(0, 4)
            m.gpu_cache_usage_perc = m.kv_active_blocks / m.kv_total_blocks
            m.gpu_prefix_cache_hit_rate = self.rng.random()
            await asyncio.sleep(0.1)

    async def _publish_kv(self) -> None:
        plane = self.component.drt.event_plane
        subject = kv_events_subject(self.component.path)
        wid = self._served.instance_id
        parent = None
        while True:
            h = next(self._hashes)
            event = RouterEvent(
                worker_id=wid,
                data=KvCacheEventData(
                    kind="stored", block_hashes=[h], parent_hash=parent
                ),
            )
            await plane.publish(subject, event.to_dict())
            parent = h
            await asyncio.sleep(0.05)

    async def stop(self) -> None:
        import contextlib

        for t in self._tasks:
            t.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await t
        self._tasks.clear()
        if self._served is not None:
            await self._served.close()
            self._served = None


def main() -> None:  # pragma: no cover - CLI entry
    import argparse

    from ..runtime.component import DistributedRuntime
    from ..runtime.config import RuntimeConfig

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator", required=True)
    p.add_argument("--component", required=True, help="namespace.component")
    args = p.parse_args()

    async def run():
        cfg = RuntimeConfig(coordinator_endpoint=args.coordinator)
        drt = DistributedRuntime(config=cfg)
        ns, _, comp = args.component.partition(".")
        worker = MockWorker(drt.namespace(ns).component(comp))
        iid = await worker.start()
        print(f"mock worker instance {iid}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
