"""Processor: tokenize, route, detokenize — the OpenAI-level middle tier.

Reference parity: ``/root/reference/examples/llm/components/processor.py``
(chat template + tokenization, route to workers, stream deltas back).
Here it composes the real stack: OpenAIPreprocessor → Backend
(incremental detokenize + stop jail) → routed core over the TpuWorker
fleet (round-robin or KV-aware per config).
"""

from __future__ import annotations

import logging

from dynamo_exp_tpu.sdk import (
    async_on_start,
    depends,
    dynamo_context,
    endpoint,
    service,
)

from .worker import TpuWorker

logger = logging.getLogger(__name__)


@service(dynamo={"namespace": "dynamo"})
class Processor:
    # Graph edge: serving this processor launches the worker fleet; the
    # actual request routing goes through build_routed_core below (the
    # depends client is round-robin-only).
    workers = depends(TpuWorker)

    model_path: str = ""
    served_model_name: str = ""
    router: str = "round-robin"  # random | round-robin | kv
    page_size: int = 16

    def __init__(self):
        self.engine = None
        self._kv_router = None

    @async_on_start
    async def build(self) -> None:
        from dynamo_exp_tpu.http.service import build_pipeline_engine
        from dynamo_exp_tpu.kv_router.router import build_routed_core
        from dynamo_exp_tpu.model_card import ModelDeploymentCard
        from dynamo_exp_tpu.models.hub import resolve_model_path
        from dynamo_exp_tpu.runtime.push_router import RouterMode
        from dynamo_exp_tpu.sdk.service import get_spec

        drt = dynamo_context["runtime"]
        path = resolve_model_path(self.model_path)
        mdc = ModelDeploymentCard.from_local_path(
            path, self.served_model_name or None
        )
        mdc.kv_cache_block_size = self.page_size
        spec = get_spec(TpuWorker)
        ep = (
            drt.namespace(spec.namespace)
            .component(spec.component_name)
            .endpoint("generate")
        )
        mode = {
            "random": RouterMode.RANDOM,
            "round-robin": RouterMode.ROUND_ROBIN,
            "kv": RouterMode.KV,
        }[self.router]
        core, self._kv_router = await build_routed_core(
            ep, mode, mdc.kv_cache_block_size
        )
        self.engine = build_pipeline_engine(mdc, core)

    @endpoint()
    async def generate(self, request: dict):
        """{"request": <OpenAI dict>} in, OpenAI chunk dicts out."""
        # Graph services boot concurrently; gate the first request on
        # the worker fleet being discoverable instead of erroring.
        await self.workers.wait_ready(1, timeout_s=120.0)
        stream = await self.engine.generate(request.get("request", request))
        async for chunk in stream:
            # Pydantic chunk objects → dicts for the wire; the Frontend
            # re-validates them on its side.
            yield chunk.model_dump(exclude_none=True)
