"""llmctl: control CLI over the live model-registration plane.

Capability parity with ``/root/reference/launch/llmctl/src/main.rs``
(:101-454): add / list / remove model registrations against the running
control plane, so operators can attach models to ingress (or detach
them) without touching workers.

    python -m dynamo_exp_tpu.llmctl --coordinator HOST:PORT \
        http add chat-model foo/v1 dynamo.TpuWorker.generate \
        [--model-path /models/foo]
    python -m dynamo_exp_tpu.llmctl --coordinator HOST:PORT http list
    python -m dynamo_exp_tpu.llmctl --coordinator HOST:PORT \
        http remove model foo/v1

Entries added here are NOT lease-scoped (no worker owns them): they
represent operator intent and persist until removed, exactly like the
reference's etcd writes from llmctl.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from .local_model import MDC_BUCKET, MODELS_PREFIX, ModelEntry

_TYPES = {"chat-model": "chat", "completion-model": "completion", "model": "both"}


def _slug(name: str) -> str:
    return name.replace("/", "--")


async def add_model(drt, args) -> int:
    entry = ModelEntry(
        name=args.model_name,
        endpoint=_qualify(args.endpoint_name, args.namespace),
        model_type=_TYPES[args.model_type],
        mdc_key=_slug(args.model_name),
    )
    if args.model_path:
        from .model_card import ModelDeploymentCard

        mdc = ModelDeploymentCard.from_local_path(
            args.model_path, args.model_name
        )
        await drt.object_store.put(
            MDC_BUCKET, entry.mdc_key, mdc.to_json().encode()
        )
    # Key carries the model type so chat + completion registrations of
    # one name coexist (and remove stays type-scoped).
    key = (
        f"{MODELS_PREFIX}{_slug(args.model_name)}/llmctl-{entry.model_type}"
    )
    await drt.discovery.kv_put(key, entry.to_bytes())
    print(f"added {entry.model_type} model {entry.name} -> {entry.endpoint}")
    return 0


async def list_models(drt, args) -> int:
    entries = await drt.discovery.kv_get_prefix(MODELS_PREFIX)
    want = _TYPES.get(args.model_type or "model", "both")
    rows = []
    for key, raw in sorted(entries.items()):
        try:
            e = ModelEntry.from_bytes(raw)
        except (ValueError, TypeError, KeyError):
            continue
        if want != "both" and e.model_type not in (want, "both"):
            continue
        rows.append((e.name, e.model_type, e.endpoint, key.rsplit("/", 1)[-1]))
    if args.json:
        print(json.dumps([
            {"name": n, "type": t, "endpoint": ep, "owner": o}
            for n, t, ep, o in rows
        ]))
        return 0
    if not rows:
        print("no models registered")
        return 0
    width = max(len(r[0]) for r in rows)
    for name, mtype, ep, owner in rows:
        print(f"{name:<{width}}  {mtype:<10}  {ep}  ({owner})")
    return 0


async def remove_model(drt, args) -> int:
    """Remove registrations of the given type only — a model registered
    as both chat and completion under one name keeps the other entry
    (type-scoped like the reference llmctl,
    ``/root/reference/launch/llmctl/src/main.rs:101-454``)."""
    want = _TYPES.get(args.model_type or "model", "both")
    prefix = f"{MODELS_PREFIX}{_slug(args.model_name)}/"
    entries = await drt.discovery.kv_get_prefix(prefix)
    removed = 0
    for key, raw in entries.items():
        try:
            e = ModelEntry.from_bytes(raw)
        except (ValueError, TypeError, KeyError):
            # Undecodable entries are unreachable by type-scoped remove;
            # the untyped 'model' remove is the escape hatch that clears
            # them (otherwise garbage keys would be undeletable forever).
            if want == "both":
                await drt.discovery.kv_delete(key)
                removed += 1
            continue
        if want != "both" and e.model_type not in (want, "both"):
            continue
        await drt.discovery.kv_delete(key)
        removed += 1
    if not removed:
        print(f"no {args.model_type} registration for {args.model_name}",
              file=sys.stderr)
        return 1
    print(f"removed {removed} registration(s) for {args.model_name}")
    return 0


def _qualify(endpoint: str, namespace: str) -> str:
    """component.endpoint or namespace.component.endpoint → dyn:// URL."""
    if endpoint.startswith("dyn://"):
        endpoint = endpoint[len("dyn://") :]
    parts = endpoint.split(".")
    if len(parts) == 2:
        parts = [namespace, *parts]
    if len(parts) != 3:
        raise SystemExit(
            f"endpoint must be [ns.]component.endpoint, got {endpoint!r}"
        )
    return "dyn://" + ".".join(parts)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl", description=__doc__)
    # Required for the control-plane planes; ``trace`` works offline
    # from recorder files (validated in run()).
    p.add_argument("--coordinator", default="", help="control plane host:port")
    p.add_argument("-n", "--namespace", default="dynamo")
    sub = p.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http", help="HTTP-served model registrations")
    hsub = http.add_subparsers(dest="command", required=True)

    add = hsub.add_parser("add")
    add.add_argument("model_type", choices=sorted(_TYPES))
    add.add_argument("model_name")
    add.add_argument("endpoint_name")
    add.add_argument("--model-path", default="", help="publish an MDC too")

    lst = hsub.add_parser("list")
    lst.add_argument("model_type", nargs="?", choices=sorted(_TYPES))
    lst.add_argument("--json", action="store_true")

    rm = hsub.add_parser("remove")
    rm.add_argument("model_type", choices=sorted(_TYPES))
    rm.add_argument("model_name")

    # Live disagg-router reconfiguration (reference: DisaggRouterConf in
    # etcd with a watch, disagg_router.rs:24-262). ``set`` takes effect
    # on running decode workers within one watch push — no restarts.
    disagg = sub.add_parser(
        "disagg", help="conditional disagg-router config (live-watched)"
    )
    dsub = disagg.add_subparsers(dest="command", required=True)
    dget = dsub.add_parser("get")
    dget.add_argument("model_name")
    dset = dsub.add_parser("set")
    dset.add_argument("model_name")
    dset.add_argument("--max-local-prefill-length", type=int, required=True)
    dset.add_argument("--max-prefill-queue-size", type=int, default=2)

    # Graceful drain: publish drain intent for an instance. The serving
    # process watches the drain prefix, republishes itself with
    # ``draining`` metadata (routers stop sending new work on their next
    # discovery snapshot), and finishes in-flight requests.
    drain = sub.add_parser(
        "drain", help="gracefully drain a worker instance (stop new work)"
    )
    drain.add_argument("instance_id", type=int)

    # Offline trace reconstruction from the telemetry recorder JSONL
    # (``DYN_TRACE_FILE``): no argument lists recorded traces; with a
    # trace_id (full/prefix) or request id, pretty-prints its span tree.
    trace = sub.add_parser(
        "trace", help="reconstruct a request's span timeline from recorder JSONL"
    )
    trace.add_argument(
        "trace_id", nargs="?", default="",
        help="trace id (full or prefix) or request id; omit to list traces",
    )
    trace.add_argument(
        "--trace-file", action="append", default=None,
        help="recorder JSONL path(s); defaults to $DYN_TRACE_FILE "
             "(rotated generations are read automatically)",
    )
    return p


def run_trace(args) -> int:
    import os

    from .telemetry import find_trace, list_traces, load_spans, render_timeline

    paths = args.trace_file or (
        [os.environ["DYN_TRACE_FILE"]] if os.environ.get("DYN_TRACE_FILE") else []
    )
    if not paths:
        print(
            "no trace files: pass --trace-file or set DYN_TRACE_FILE",
            file=sys.stderr,
        )
        return 2
    spans = load_spans(paths)
    if not spans:
        print("no spans recorded", file=sys.stderr)
        return 1
    if not args.trace_id:
        for tid, n, dur, stage in list_traces(spans):
            print(f"{tid}  {n:3d} spans  {dur * 1e3:9.1f}ms  {stage}")
        return 0
    group = find_trace(spans, args.trace_id)
    if not group:
        print(f"no trace matching {args.trace_id!r}", file=sys.stderr)
        return 1
    print(render_timeline(group))
    return 0


async def drain_instance(drt, args) -> int:
    from .runtime.component import DRAIN_PREFIX

    live = {
        i.instance_id
        for i in await drt.discovery.list_instances("")
    }
    if args.instance_id not in live:
        print(f"instance {args.instance_id} is not live", file=sys.stderr)
        return 1
    await drt.discovery.kv_put(f"{DRAIN_PREFIX}{args.instance_id}", b"1")
    print(
        f"drain requested for instance {args.instance_id}; routers stop "
        "sending new work once the worker republishes its metadata"
    )
    return 0


async def get_disagg(drt, args) -> int:
    from .disagg.config import DisaggConfig, disagg_config_key

    raw = await drt.discovery.kv_get(disagg_config_key(args.model_name))
    cfg = DisaggConfig.from_bytes(raw) if raw else DisaggConfig()
    print(json.dumps({"model": args.model_name, **cfg.__dict__}, indent=2))
    return 0


async def set_disagg(drt, args) -> int:
    from .disagg.config import DisaggConfig, disagg_config_key

    cfg = DisaggConfig(
        max_local_prefill_length=args.max_local_prefill_length,
        max_prefill_queue_size=args.max_prefill_queue_size,
    )
    await drt.discovery.kv_put(disagg_config_key(args.model_name), cfg.to_bytes())
    print(f"disagg config for {args.model_name} updated: {cfg}")
    return 0


async def run(args) -> int:
    from .runtime.component import DistributedRuntime
    from .runtime.config import RuntimeConfig

    if args.plane == "trace":  # offline: reads recorder files, no cluster
        return run_trace(args)
    if not args.coordinator:
        print("--coordinator is required for this command", file=sys.stderr)
        return 2
    drt = DistributedRuntime(
        config=RuntimeConfig(coordinator_endpoint=args.coordinator)
    )
    try:
        if args.plane == "drain":
            return await drain_instance(drt, args)
        if args.plane == "disagg":
            if args.command == "get":
                return await get_disagg(drt, args)
            return await set_disagg(drt, args)
        if args.command == "add":
            return await add_model(drt, args)
        if args.command == "list":
            return await list_models(drt, args)
        return await remove_model(drt, args)
    finally:
        await drt.close()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
