"""OpenAI-compatible HTTP ingress (aiohttp).

Capability parity with ``/root/reference/lib/llm/src/http/service/``:
``/v1/chat/completions``, ``/v1/completions``, ``/v1/models``, ``/metrics``,
``/health``; always streams from the engine, aggregates for
``stream=false``; per-model engine registry with dynamic attach/detach;
client disconnect kills the request context.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any

from aiohttp import web

from ..protocols.aggregator import aggregate_chat_stream, aggregate_completion_stream
from ..protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    CompletionChunk,
    CompletionRequest,
    ModelInfo,
    ModelList,
)
from ..preprocessor.preprocessor import InvalidRequestError, PromptTooLongError
from ..protocols.sse import encode_done, encode_frame
from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, AsyncEngineContext, DeadlineExceededError
from ..runtime.push_router import NoInstancesError, RecoveryExhaustedError
from ..telemetry import get_telemetry, span
from .admission import AdmissionController, RequestShedError, parse_priority
from .metrics import CONTENT_TYPE_LATEST, ServiceMetrics

# Clients hint how soon to retry a 503 (no instances / breaker open):
# instance churn resolves within a lease TTL or breaker cooldown.
RETRY_AFTER_S = "1"

logger = logging.getLogger(__name__)


class ModelManager:
    """Per-model engine registry with dynamic attach/detach."""

    def __init__(self):
        self._chat: dict[str, AsyncEngine] = {}
        self._completion: dict[str, AsyncEngine] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self._chat[name] = engine

    def add_completion_model(self, name: str, engine: AsyncEngine) -> None:
        self._completion[name] = engine

    def remove_model(self, name: str) -> None:
        self._chat.pop(name, None)
        self._completion.pop(name, None)

    def remove_chat_model(self, name: str) -> None:
        self._chat.pop(name, None)

    def remove_completion_model(self, name: str) -> None:
        self._completion.pop(name, None)

    def chat_engine(self, name: str) -> AsyncEngine | None:
        return self._chat.get(name)

    def completion_engine(self, name: str) -> AsyncEngine | None:
        return self._completion.get(name)

    def model_names(self) -> list[str]:
        return sorted(set(self._chat) | set(self._completion))


class HttpService:
    def __init__(
        self,
        manager: ModelManager | None = None,
        metrics: ServiceMetrics | None = None,
        host: str = "0.0.0.0",
        port: int = 8080,
        request_template=None,
        admission: AdmissionController | None = None,
        slo=None,
    ):
        self.manager = manager or ModelManager()
        self.metrics = metrics or ServiceMetrics()
        self.host = host
        self.port = port
        # Server-side defaults for sparse request bodies (reference:
        # request_template.rs applied in dynamo-run's HTTP input).
        self.request_template = request_template
        # Overload protection: bounded in-flight work with priority-aware
        # shedding (docs/fault_tolerance.md). None = accept unboundedly
        # (embedded/test deployments that bound load elsewhere).
        self.admission = admission
        # SLO attribution (docs/observability.md "SLO attribution &
        # goodput"): a telemetry.SloAttribution measuring per-request
        # TTFT/ITL at this edge against the configured targets — the
        # same code path the cluster simulator counts SimReport goodput
        # with, and the window the live SLO planner reads its pressure
        # inputs from. None = not measured.
        self.slo = slo
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self._chat)
        self.app.router.add_post("/v1/completions", self._completions)
        self.app.router.add_get("/v1/models", self._models)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_get("/health", self._health)
        self.app.router.add_get("/live", self._health)
        self._runner: web.AppRunner | None = None

    # --- lifecycle ----------------------------------------------------
    async def start(self) -> int:
        """Start serving; returns the bound port."""
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            sockets = getattr(s, "_server", None)
            if sockets and sockets.sockets:
                self.port = sockets.sockets[0].getsockname()[1]
        logger.info("HTTP service listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # --- handlers -----------------------------------------------------
    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "healthy", "models": self.manager.model_names()}
        )

    async def _models(self, request: web.Request) -> web.Response:
        listing = ModelList(
            data=[ModelInfo(id=name) for name in self.manager.model_names()]
        )
        return web.json_response(listing.model_dump())

    async def _metrics(self, request: web.Request) -> web.Response:
        # ServiceMetrics.render() already merges the telemetry registry.
        return web.Response(
            body=self.metrics.render(), content_type="text/plain", charset="utf-8"
        )

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_llm(
            request,
            parse=ChatCompletionRequest.model_validate,
            lookup=self.manager.chat_engine,
            chunk_type=ChatCompletionChunk,
            aggregate=aggregate_chat_stream,
            endpoint="chat_completions",
        )

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_llm(
            request,
            parse=_parse_completion_request,
            lookup=self.manager.completion_engine,
            chunk_type=CompletionChunk,
            aggregate=aggregate_completion_stream,
            endpoint="completions",
            expand_batch=_expand_completion_batch,
        )

    async def _serve_llm(
        self,
        request: web.Request,
        parse,
        lookup,
        chunk_type,
        aggregate,
        endpoint: str,
        expand_batch=None,
    ) -> web.StreamResponse:
        try:
            payload = await request.json()
            if self.request_template is not None:
                payload = self.request_template.apply(payload)
            # End-to-end deadline: an explicit per-request budget via the
            # ``timeout_s`` body field or ``X-Request-Timeout-S`` header.
            # Popped before parsing so strict models don't reject it.
            timeout_s = _request_timeout_s(payload, request)
            req = parse(payload)
            priority = _request_priority(req, request)
            # Canonicalize into the forwarded payload: the engine's
            # preemption victim selection must see the same class the
            # edge admitted under — a header-only spelling would
            # otherwise never reach the preprocessor.
            if isinstance(payload, dict):
                payload["priority"] = priority
        except Exception as e:
            return _error_response(400, f"invalid request: {e}")
        engine = lookup(req.model)
        if engine is None:
            return _error_response(
                404, f"model {req.model!r} not found", err_type="model_not_found"
            )
        if self.admission is not None:
            # Overload protection: bounded in-flight work. Above the shed
            # watermark lower-priority classes get 429; at the hard cap
            # everything gets 503. Both carry Retry-After — the request
            # was fine, the service is busy.
            try:
                self.admission.acquire(priority)
            except RequestShedError as e:
                self.metrics.count_shed(req.model, endpoint, e.status)
                return _error_response(
                    e.status,
                    str(e),
                    err_type=(
                        "service_overloaded" if e.status == 503 else "request_shed"
                    ),
                    headers={"Retry-After": str(max(int(e.retry_after_s), 1))},
                )
        try:
            return await self._serve_admitted(
                request,
                req,
                engine,
                timeout_s,
                payload=payload,
                chunk_type=chunk_type,
                aggregate=aggregate,
                endpoint=endpoint,
                expand_batch=expand_batch,
                priority=priority,
            )
        finally:
            # Released only when the response is complete (the SSE stream
            # has drained) — in-flight covers the full request lifetime.
            if self.admission is not None:
                self.admission.release()

    async def _serve_admitted(
        self,
        request: web.Request,
        req,
        engine: AsyncEngine,
        timeout_s: float | None,
        *,
        payload,
        chunk_type,
        aggregate,
        endpoint: str,
        expand_batch,
        priority: int = 1,
    ) -> web.StreamResponse:
        # SLO attribution clock: TTFT/ITL are measured from request
        # arrival at this edge — the latency the client experiences,
        # which is what the targets are promises about.
        t_arrival = time.monotonic()
        # Per-request latency marks filled in by _typed_chunks below
        # (first/last content chunk, cumulative token watermark).
        lat = {"first": 0.0, "last": 0.0, "tokens": 0}
        # OpenAI allows a list of prompts in one completion request; fan the
        # batch out as independent sub-requests with re-indexed choices.
        sub_payloads = expand_batch(payload) if expand_batch else [payload]
        # One context per sub-request: a finished sub-stream must not stop
        # its batch siblings; disconnect kills them all.
        ctxs = [AsyncEngineContext() for _ in sub_payloads]
        if timeout_s is not None:
            for c in ctxs:
                c.start_timeout(timeout_s)
        ctx = _FanoutContext(ctxs)
        request_type = "stream" if req.stream else "unary"
        streaming = req.stream
        # Root span of the request's trace: everything below (preprocess,
        # routing, engine stages, KV transfer) parents onto this via the
        # trace contextvar, and log lines emitted during handling carry
        # its trace_id.
        with span(
            "http_request",
            request_id=ctx.id,
            model=req.model,
            endpoint=endpoint,
            request_type=request_type,
        ) as root, self.metrics.track(req.model, endpoint, request_type) as tracker:
            # Inside the trace context: this line (and everything logged
            # below it while handling) carries the trace_id in JSONL mode.
            logger.info(
                "request %s: model=%s endpoint=%s type=%s",
                ctx.id, req.model, endpoint, request_type,
            )
            try:
                streams = [
                    await engine.generate(p, c) for p, c in zip(sub_payloads, ctxs)
                ]
            except PromptTooLongError as e:
                tracker.status = "rejected"
                root.set(status="rejected")
                return _error_response(400, str(e), err_type="context_length_exceeded")
            except InvalidRequestError as e:
                tracker.status = "rejected"
                root.set(status="rejected")
                return _error_response(400, str(e), err_type="invalid_request_error")
            except NoInstancesError as e:
                # No live/healthy workers (includes breaker-open). The
                # condition is transient — tell clients when to retry.
                tracker.status = "unavailable"
                root.set(status="unavailable")
                return _error_response(
                    503,
                    str(e) or "no instances available",
                    err_type="service_unavailable",
                    headers={"Retry-After": RETRY_AFTER_S},
                )
            except DeadlineExceededError as e:
                tracker.status = "deadline"
                root.set(status="deadline")
                return _error_response(
                    504, str(e), err_type="deadline_exceeded"
                )
            except Exception as e:
                logger.exception("engine rejected request")
                tracker.status = "error"
                root.set(status="error")
                return _error_response(500, str(e))

            async def _typed_chunks():
                for idx, stream in enumerate(streams):
                    # Resumable-stream belt-and-braces: chunks carry a
                    # cumulative sequence index (``seq_index``); anything
                    # at or below the emitted watermark is a replayed
                    # duplicate from a mid-stream failover splice and is
                    # dropped here, so the client-facing SSE stream is
                    # duplicate-free even if a lower layer misbehaves.
                    high = 0  # emitted watermark (cumulative tokens)
                    last = 0  # previous chunk's index, arrival order
                    async for item in stream:
                        if streaming:
                            tracker.first_token()
                        chunk = (
                            chunk_type.model_validate(item)
                            if isinstance(item, dict)
                            else item
                        )
                        si = getattr(chunk, "seq_index", None)
                        if si is not None:
                            if si <= high:
                                get_telemetry().tokens_deduplicated.inc(
                                    max(si - last, 0)
                                )
                                last = si
                                continue
                            delta = si - high
                            last = high = si
                        else:
                            delta = 1
                        if chunk.choices:
                            # SLO marks: first/last content chunk and
                            # cumulative tokens (seq_index watermark
                            # delta when present, chunk count floor
                            # otherwise) — the per-request TTFT/ITL fed
                            # to the edge SLO attribution.
                            now = time.monotonic()
                            if not lat["first"]:
                                lat["first"] = now
                            lat["last"] = now
                            lat["tokens"] += max(delta, 1)
                        if idx and chunk.choices:
                            for choice in chunk.choices:
                                choice.index = idx
                        yield chunk

            if not req.stream:
                try:
                    full = await aggregate(_typed_chunks())
                except RecoveryExhaustedError as e:
                    # A resumable stream broke more times than
                    # max_recoveries allows: the upstream fleet kept
                    # dying mid-generation — a gateway failure, not a
                    # client error and not "no instances".
                    tracker.status = "recovery_exhausted"
                    root.set(status="recovery_exhausted")
                    ctx.kill()
                    return _error_response(
                        502, str(e), err_type="bad_gateway"
                    )
                except NoInstancesError as e:
                    tracker.status = "unavailable"
                    root.set(status="unavailable")
                    ctx.kill()
                    return _error_response(
                        503,
                        str(e) or "no instances available",
                        err_type="service_unavailable",
                        headers={"Retry-After": RETRY_AFTER_S},
                    )
                except DeadlineExceededError as e:
                    tracker.status = "deadline"
                    root.set(status="deadline")
                    ctx.kill()
                    return _error_response(504, str(e), err_type="deadline_exceeded")
                except Exception as e:
                    logger.exception("request failed")
                    tracker.status = "error"
                    root.set(status="error")
                    ctx.kill()
                    return _error_response(500, str(e))
                self._record_slo(priority, t_arrival, lat, root)
                return web.json_response(full.model_dump(exclude_none=True))

            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                }
            )
            await resp.prepare(request)
            try:
                async for chunk in _typed_chunks():
                    frame = Annotated.from_data(chunk.model_dump(exclude_none=True))
                    await resp.write(encode_frame(frame).encode())
                await resp.write(encode_done().encode())
                # Attributed only on a fully drained stream: a request
                # that errored or lost its client is not goodput and
                # its truncated latencies would poison the window.
                self._record_slo(priority, t_arrival, lat, root)
            except (ConnectionResetError, asyncio.CancelledError):
                # Client went away: kill generation immediately.
                logger.info("client disconnected; killing request %s", ctx.id)
                tracker.status = "disconnect"
                root.set(status="disconnect")
                ctx.kill()
                raise
            except Exception as e:
                logger.exception("stream failed mid-flight")
                tracker.status = "error"
                root.set(status="error")
                ctx.kill()
                err = Annotated.from_error(str(e))
                await resp.write(encode_frame(err).encode())
            await resp.write_eof()
            return resp

    def _record_slo(
        self, priority: int, t_arrival: float, lat: dict, root=None
    ) -> None:
        """Feed one completed request into the SLO attribution: TTFT =
        arrival -> first content chunk, ITL = mean inter-token interval
        after it (None for single-token responses — never a violation).
        The same edge measurements stamp the root http_request span
        (``ttft_s`` / ``itl_s`` / ``latency_s``) — the ground truth the
        request-anatomy component sum is checked against
        (telemetry/anatomy.py, `llmctl trace --why`)."""
        if not lat["first"]:
            return
        ttft = max(lat["first"] - t_arrival, 0.0)
        itl = None
        if lat["tokens"] > 1:
            itl = max(lat["last"] - lat["first"], 0.0) / (lat["tokens"] - 1)
        if root is not None:
            root.set(
                ttft_s=round(ttft, 6),
                latency_s=round(max(lat["last"] - t_arrival, 0.0), 6),
                tokens=lat["tokens"],
                priority=priority,
            )
            if itl is not None:
                root.set(itl_s=round(itl, 6))
        if self.slo is None:
            return
        self.slo.record(priority, ttft_s=ttft, itl_s=itl)


class _FanoutContext:
    """Kill/stop fan-out over a batch's per-sub-request contexts."""

    def __init__(self, ctxs: list[AsyncEngineContext]):
        self._ctxs = ctxs
        self.id = ctxs[0].id if ctxs else ""

    def kill(self) -> None:
        for c in self._ctxs:
            c.kill()

    def stop_generating(self) -> None:
        for c in self._ctxs:
            c.stop_generating()


def _parse_completion_request(payload: dict) -> CompletionRequest:
    return CompletionRequest.model_validate(payload)


def _expand_completion_batch(payload: dict) -> list[dict]:
    """Split a multi-prompt completion payload into per-prompt payloads."""
    prompt = payload.get("prompt")
    if isinstance(prompt, list) and prompt and not isinstance(prompt[0], int):
        return [{**payload, "prompt": p} for p in prompt]
    return [payload]


def _request_priority(req: Any, request: web.Request) -> int:
    """Admission priority class: the body/nvext ``priority`` field wins
    over the ``X-Request-Priority`` header; absent means ``normal``.
    Invalid spellings raise (the caller maps to 400)."""
    raw = None
    getter = getattr(req, "request_priority", None)
    if getter is not None:
        raw = getter()
    if raw is None:
        raw = request.headers.get("X-Request-Priority")
    return parse_priority(raw)


def _request_timeout_s(payload: Any, request: web.Request) -> float | None:
    """Per-request deadline budget: body ``timeout_s`` wins over the
    ``X-Request-Timeout-S`` header; absent/invalid means no deadline."""
    raw = None
    if isinstance(payload, dict):
        raw = payload.pop("timeout_s", None)
    if raw is None:
        raw = request.headers.get("X-Request-Timeout-S")
    if raw is None:
        return None
    try:
        timeout_s = float(raw)
    except (TypeError, ValueError):
        raise ValueError(f"timeout_s must be a number, got {raw!r}") from None
    if timeout_s <= 0:
        raise ValueError("timeout_s must be positive")
    return timeout_s


def _error_response(
    status: int,
    message: str,
    err_type: str = "invalid_request_error",
    headers: dict[str, str] | None = None,
) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": err_type, "code": status}},
        status=status,
        headers=headers,
    )


def build_pipeline_engine(mdc, core_engine) -> AsyncEngine:
    """preprocessor -> backend -> core engine, as one OpenAI-level engine."""
    from ..backend import Backend
    from ..preprocessor.preprocessor import OpenAIPreprocessor
    from ..runtime.pipeline import build_pipeline

    pre = OpenAIPreprocessor(mdc)
    backend = Backend(core_engine, pre.tokenizer)
    return build_pipeline([pre], backend)
