"""Tests for the runtime core: cancellation, components, routing, pipeline."""

import asyncio

import pytest

from dynamo_exp_tpu.runtime import (
    Annotated,
    AsyncEngineContext,
    CancellationToken,
    DistributedRuntime,
    EngineError,
    LambdaEngine,
    MapOperator,
    Pool,
    PushRouter,
    RouterMode,
    Runtime,
    annotated_stream,
    build_pipeline,
)


# --- cancellation ------------------------------------------------------
@pytest.mark.asyncio
async def test_cancellation_token_hierarchy():
    root = CancellationToken()
    child = root.child_token()
    grandchild = child.child_token()
    assert not grandchild.is_cancelled()
    root.cancel()
    assert child.is_cancelled() and grandchild.is_cancelled()


@pytest.mark.asyncio
async def test_run_until_cancelled_aborts():
    token = CancellationToken()

    async def forever():
        await asyncio.sleep(100)
        return "done"

    task = asyncio.ensure_future(token.run_until_cancelled(forever()))
    await asyncio.sleep(0.01)
    token.cancel()
    assert await task is None


# --- component model ---------------------------------------------------
async def echo_handler(request, context):
    for tok in request["tokens"]:
        yield Annotated.from_data({"token": tok}).to_dict()


@pytest.mark.asyncio
async def test_serve_and_call_endpoint():
    drt = DistributedRuntime.detached()
    ep = drt.namespace("test").component("worker").endpoint("generate")
    served = await ep.serve_endpoint(echo_handler)
    client = await ep.client()
    await client.wait_for_instances(1, timeout=2)

    router = PushRouter(client, RouterMode.RANDOM)
    stream = await router.generate({"tokens": [1, 2, 3]})
    out = [item["token"] async for item in stream]
    assert out == [1, 2, 3]
    await served.close()
    await drt.close()


@pytest.mark.asyncio
async def test_lease_revoke_removes_instance():
    drt = DistributedRuntime.detached()
    ep = drt.namespace("test").component("worker").endpoint("generate")
    served = await ep.serve_endpoint(echo_handler)
    client = await ep.client()
    await client.wait_for_instances(1, timeout=2)
    await served.lease.revoke()
    await asyncio.sleep(0.02)
    assert client.instances == []
    await drt.close()


@pytest.mark.asyncio
async def test_round_robin_spreads_requests():
    drt = DistributedRuntime.detached()
    ep = drt.namespace("test").component("worker").endpoint("generate")
    hits = {1: 0, 2: 0}

    def make_handler(wid):
        async def handler(request, context):
            hits[wid] += 1
            yield Annotated.from_data({"worker": wid}).to_dict()

        return handler

    lease_a = await drt.discovery.create_lease()
    lease_b = await drt.discovery.create_lease()
    await ep.serve_endpoint(make_handler(1), lease=lease_a)
    await ep.serve_endpoint(make_handler(2), lease=lease_b)
    client = await ep.client()
    await client.wait_for_instances(2, timeout=2)
    router = PushRouter(client, RouterMode.ROUND_ROBIN)
    for _ in range(4):
        stream = await router.generate({"tokens": []})
        async for _ in stream:
            pass
    assert hits[1] == 2 and hits[2] == 2
    await drt.close()


@pytest.mark.asyncio
async def test_direct_routing():
    drt = DistributedRuntime.detached()
    ep = drt.namespace("test").component("worker").endpoint("generate")

    def make_handler(wid):
        async def handler(request, context):
            yield Annotated.from_data({"worker": wid}).to_dict()

        return handler

    a = await ep.serve_endpoint(make_handler("a"), lease=await drt.discovery.create_lease())
    await ep.serve_endpoint(make_handler("b"), lease=await drt.discovery.create_lease())
    client = await ep.client()
    await client.wait_for_instances(2, timeout=2)
    router = PushRouter(client, RouterMode.DIRECT)
    stream = await router.generate_direct({"tokens": []}, a.instance_id)
    out = [item async for item in stream]
    assert out == [{"worker": "a"}]
    await drt.close()


@pytest.mark.asyncio
async def test_error_frames_raise_engine_error():
    async def failing(request, context):
        yield Annotated.from_data({"ok": 1}).to_dict()
        yield Annotated.from_error("boom").to_dict()

    drt = DistributedRuntime.detached()
    ep = drt.namespace("test").component("worker").endpoint("generate")
    await ep.serve_endpoint(failing)
    client = await ep.client()
    await client.wait_for_instances(1, timeout=2)
    router = PushRouter(client)
    stream = await router.generate({})
    with pytest.raises(EngineError, match="boom"):
        async for _ in stream:
            pass
    await drt.close()


@pytest.mark.asyncio
async def test_annotated_stream_wraps_engine_errors():
    async def explode(request, ctx):
        raise ValueError("engine exploded")
        yield  # pragma: no cover

    engine = LambdaEngine(explode)
    frames = [f async for f in annotated_stream(engine, {})]
    assert Annotated.from_dict(frames[-1]).is_error()


@pytest.mark.asyncio
async def test_scrape_stats():
    drt = DistributedRuntime.detached()
    comp = drt.namespace("test").component("worker")
    await comp.endpoint("generate").serve_endpoint(
        echo_handler, stats_handler=lambda: {"kv_active_blocks": 5}
    )
    stats = await comp.scrape_stats()
    assert len(stats) == 1
    (s,) = stats.values()
    assert s["kv_active_blocks"] == 5
    await drt.close()


# --- pipeline ----------------------------------------------------------
@pytest.mark.asyncio
async def test_pipeline_composition():
    async def sink_gen(request, ctx):
        for t in request["tokens"]:
            yield t

    sink = LambdaEngine(sink_gen)
    double_in = MapOperator(map_request=lambda r: {"tokens": [t * 2 for t in r["tokens"]]})
    plus_one_out = MapOperator(map_response_item=lambda t: t + 1)
    engine = build_pipeline([plus_one_out, double_in], sink)
    stream = await engine.generate({"tokens": [1, 2, 3]})
    assert [t async for t in stream] == [3, 5, 7]


# --- pool --------------------------------------------------------------
@pytest.mark.asyncio
async def test_pool_acquire_release():
    pool = Pool([1, 2])
    a = await pool.acquire()
    b = await pool.acquire()
    assert pool.available == 0
    waiter = asyncio.ensure_future(pool.acquire())
    await asyncio.sleep(0.01)
    assert not waiter.done()
    a.release()
    c = await asyncio.wait_for(waiter, 1)
    assert c.value == a._value if hasattr(a, "_value") else True
    b.release()
    c.release()
    assert pool.available == 2


@pytest.mark.asyncio
async def test_runtime_blocking_and_shutdown():
    rt = Runtime(num_blocking_threads=2)
    assert await rt.run_blocking(lambda: 42) == 42
    rt.shutdown()
    assert rt.is_shutdown()
    await rt.close()


@pytest.mark.asyncio
async def test_two_endpoints_share_primary_lease_without_clobbering():
    """Regression: serving two endpoints under the default (shared primary)
    lease must not overwrite each other's handler or discovery entry."""
    drt = DistributedRuntime.detached()
    comp = drt.namespace("test").component("worker")

    async def gen_handler(request, ctx):
        yield Annotated.from_data("gen").to_dict()

    async def stats_handler(request, ctx):
        yield Annotated.from_data("stats").to_dict()

    await comp.endpoint("generate").serve_endpoint(gen_handler)
    await comp.endpoint("load_metrics").serve_endpoint(stats_handler)

    c1 = await comp.endpoint("generate").client()
    c2 = await comp.endpoint("load_metrics").client()
    await c1.wait_for_instances(1, timeout=2)
    await c2.wait_for_instances(1, timeout=2)
    s1 = await PushRouter(c1).generate({})
    s2 = await PushRouter(c2).generate({})
    assert [x async for x in s1] == ["gen"]
    assert [x async for x in s2] == ["stats"]
    await drt.close()
