"""Fleet-wide prefix sharing (docs/prefix_sharing.md): radix index
units, refcounted copy-on-write page manager behavior, shared-vs-private
token identity, pending-fill (in-flight) sharing, suffix-only disagg
transfer, and the aggregate-context capacity win."""

import asyncio

import numpy as np
import pytest

from dynamo_exp_tpu.engine import EngineConfig, KvPageManager, TPUEngine
from dynamo_exp_tpu.kv import PrefixIndex
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput
from dynamo_exp_tpu.tokens import compute_block_hashes_for_seq

PS = 8


# ------------------------------------------------------------ radix index
def _chain(tokens, ps=4):
    return compute_block_hashes_for_seq(tokens, ps)


def test_index_insert_and_match_compressed_run():
    idx = PrefixIndex()
    toks = list(range(1, 17))
    hashes = _chain(toks)  # 4 blocks of 4
    parent = None
    for i, h in enumerate(hashes):
        assert idx.insert(parent, h, tokens=toks[i * 4 : (i + 1) * 4])
        parent = h
    assert idx.num_blocks == 4
    assert idx.match_hashes(hashes) == hashes
    assert idx.match_hashes(hashes[:2]) == hashes[:2]
    # A foreign chain matches nothing.
    assert idx.match_hashes(_chain(list(range(50, 66)))) == []
    # Re-insert is a refresh, not a duplicate.
    assert not idx.insert(None, hashes[0])
    assert idx.num_blocks == 4


def test_index_split_on_divergence():
    idx = PrefixIndex()
    a = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]
    b = a[:8] + [90, 91, 92, 93]
    ha, hb = _chain(a), _chain(b)
    parent = None
    for h in ha:
        idx.insert(parent, h)
        parent = h
    # Diverging insert splits the compressed run at block 2.
    idx.insert(hb[1], hb[2])
    assert idx.match_hashes(ha) == ha
    assert idx.match_hashes(hb) == hb
    assert idx.num_blocks == 4  # 3 shared-chain blocks + 1 divergent


def test_index_remove_orphans_and_reattach():
    idx = PrefixIndex()
    toks = list(range(1, 17))
    hashes = _chain(toks)
    parent = None
    for h in hashes:
        idx.insert(parent, h)
        parent = h
    # Evicting a middle block detaches the suffix (no root-anchored
    # match past the hole) without destroying it.
    idx.remove(hashes[1])
    assert idx.match_hashes(hashes) == hashes[:1]
    assert idx.num_blocks == 3
    assert idx.num_orphans == 2
    # Re-registering the missing block re-attaches the suffix.
    idx.insert(hashes[0], hashes[1])
    assert idx.match_hashes(hashes) == hashes
    assert idx.num_orphans == 0


def test_index_partial_match_needs_tokens():
    idx = PrefixIndex()
    toks = list(range(10, 22))  # 3 blocks of 4
    hashes = _chain(toks)
    idx.insert(None, hashes[0], tokens=toks[:4])
    idx.insert(hashes[0], hashes[1], tokens=toks[4:8])
    # Tail [14, 15] is a prefix of block 1's tokens.
    assert idx.partial_match(hashes[0], toks[4:6]) == (hashes[1], 2)
    # Mismatching tail, empty tail, missing parent: no match.
    assert idx.partial_match(hashes[0], [99]) is None
    assert idx.partial_match(hashes[0], []) is None
    assert idx.partial_match(12345, toks[4:6]) is None
    # Blocks indexed hash-only (router side) never partial-match.
    idx2 = PrefixIndex()
    idx2.insert(None, hashes[0])
    assert idx2.partial_match(None, toks[:2]) is None


def test_index_payloads():
    idx = PrefixIndex()
    h = _chain([1, 2, 3, 4])[0]
    idx.insert(None, h, payload=41)
    assert idx.payload(h) == 41
    idx.set_payload(h, 42)
    assert idx.payloads_for([h]) == [42]
    idx.remove(h)
    assert idx.payload(h) is None


# ------------------------------------------------------------ page manager
def _register_chain(kv, tokens):
    hashes = compute_block_hashes_for_seq(tokens, kv.page_size)
    alloc = kv.allocate_sequence(tokens, max_pages=64, request_id="seed")
    parent = None
    for i, h in enumerate(hashes):
        kv.register_full_page(
            alloc.page_ids[i], h, parent_hash=parent,
            tokens=tokens[i * kv.page_size : (i + 1) * kv.page_size],
        )
        parent = h
    return alloc, hashes


def test_manager_concurrent_same_prompt_shares_pending_pages():
    kv = KvPageManager(num_pages=16, page_size=4)
    prompt = list(range(1, 14))  # 3 full blocks + 1 tail token
    a = kv.allocate_sequence(prompt, max_pages=8, request_id="a")
    used_after_a = kv.active_pages
    # Second identical admission BEFORE any prefill: attaches A's
    # pending pages, waits on their fill.
    b = kv.allocate_sequence(prompt, max_pages=8, request_id="b")
    assert b.page_ids[:3] == a.page_ids[:3]
    assert b.cached_len == 12
    assert set(b.wait_fill) == set(a.page_ids[:3])
    # Only B's private tail page was newly taken.
    assert kv.active_pages == used_after_a + 1
    assert kv.shared_pages == 3
    assert kv.prefix_hits["shared"] == 3
    # A dispatches its fill: B unblocks.
    assert kv.fill_state(a.page_ids[0]) == "pending"
    kv.mark_filled(a.page_ids[:3])
    assert all(kv.fill_state(p) == "filled" for p in b.page_ids[:3])


def test_manager_orphaned_fill_claim_and_garbage_unregister():
    kv = KvPageManager(num_pages=16, page_size=4)
    prompt = list(range(1, 10))  # 2 full blocks + tail
    a = kv.allocate_sequence(prompt, max_pages=8, request_id="a")
    b = kv.allocate_sequence(prompt, max_pages=8, request_id="b")
    # A dies before filling: its pending pages orphan; B claims.
    kv.abort_fills("a", a.page_ids)
    kv.release_sequence(a.page_ids)
    assert kv.fill_state(b.page_ids[0]) == "orphaned"
    kv.claim_fill(b.page_ids[0], "b")
    assert kv.fill_state(b.page_ids[0]) == "pending"
    # An unfilled registered page whose LAST ref drops unregisters
    # (garbage bytes must never be matchable) instead of parking.
    hashes = compute_block_hashes_for_seq(prompt, 4)
    kv.abort_fills("b", b.page_ids)
    kv.release_sequence(b.page_ids)
    assert kv.match_prefix(prompt) == ([], [])
    assert hashes[0] not in kv.index


def test_manager_full_cover_keeps_all_pages_shared():
    kv = KvPageManager(num_pages=16, page_size=4)
    prompt = list(range(1, 9))  # exactly 2 blocks
    a, _ = _register_chain(kv, prompt)
    kv.mark_filled(a.page_ids)
    kv.release_sequence(a.page_ids)
    b = kv.allocate_sequence(prompt, max_pages=8, request_id="b")
    # The old trim re-prefilled a whole page; now the entire match
    # attaches and only the last token recomputes.
    assert b.page_ids == a.page_ids
    assert b.cached_len == len(prompt) - 1
    assert b.shared_tail is None  # aligned: no divergent write coming


def test_manager_partial_tail_attach_and_cow():
    kv = KvPageManager(num_pages=16, page_size=4)
    owner = list(range(1, 9))  # 2 registered blocks
    a, hashes = _register_chain(kv, owner)
    kv.mark_filled(a.page_ids)
    # B's prompt ends inside A's second block.
    b = kv.allocate_sequence(owner[:6], max_pages=8, request_id="b")
    assert b.shared_tail == (a.page_ids[1], 2)
    assert b.cached_len == 5  # everything but the last token
    assert b.page_ids == a.page_ids  # no fresh page at all
    # Divergent write with A still holding refs: COW to a new page.
    new_pid = kv.make_private(a.page_ids[1])
    assert new_pid not in (None, a.page_ids[1])
    assert kv.cow_copies == 1
    # Sole-holder case: unregister-in-place, no copy.
    kv.release_sequence([new_pid])
    kv.release_sequence(a.page_ids)  # A's refs gone; B still holds
    pid = b.page_ids[0]
    assert kv.make_private(pid) == pid
    assert hashes[0] not in kv.index
    assert kv.cow_copies == 1


def test_manager_sharing_off_is_private_copy_baseline():
    kv = KvPageManager(num_pages=16, page_size=4, sharing=False)
    prompt = list(range(1, 9))
    a, _ = _register_chain(kv, prompt)
    b = kv.allocate_sequence(prompt, max_pages=8, request_id="b")
    assert set(a.page_ids).isdisjoint(b.page_ids)
    assert b.cached_len == 0 and b.wait_fill == []
    assert kv.prefix_hits["shared"] == 0


def test_manager_refcounted_eviction_and_lease_pins():
    kv = KvPageManager(num_pages=4, page_size=4)
    prompt = list(range(1, 5))
    a, hashes = _register_chain(kv, prompt)
    kv.mark_filled(a.page_ids)
    b = kv.allocate_sequence(prompt + [9], max_pages=8, request_id="b")
    lease = kv.grant_lease(a.page_ids[:1], ttl_s=60.0)
    kv.release_sequence(a.page_ids)
    kv.release_sequence(b.page_ids)
    # Page 0 still pinned by the lease: exhausting the pool must not
    # evict it (a page leaves G1 only at refcount zero).
    assert kv.allocate_page() is not None  # b's tail page reclaimed
    assert kv.allocate_page() is not None
    assert kv.allocate_page() is not None
    assert kv.allocate_page() is None  # only the leased page remains
    assert hashes[0] in kv.index
    kv.confirm_lease(lease)
    assert kv.allocate_page() is not None  # now evictable


# --------------------------------------------------------------- engines
def make_engine(sharing=True, slots=4, pages=96, spec="off", **kw):
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=slots,
        page_size=PS,
        num_pages=pages,
        max_model_len=256,
        eos_token_ids=[],
        prefix_sharing=sharing,
        spec_mode=spec,
        **kw,
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def run_req(engine, prompt, n=6, seed=None, temperature=None,
                  freq_pen=None):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = n
    b.stop_conditions.ignore_eos = True
    if seed is not None:
        b.sampling_options.seed = seed
    if temperature is not None:
        b.sampling_options.temperature = temperature
    if freq_pen is not None:
        b.sampling_options.frequency_penalty = freq_pen
    stream = await engine.generate(b.to_dict())
    tokens = []
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
    return tokens


def _prefix_prompts(n, prefix_tokens, suffix_tokens, rs):
    prefix = rs.randint(3, 200, size=prefix_tokens).tolist()
    return [
        prefix + rs.randint(3, 200, size=suffix_tokens).tolist()
        for _ in range(n)
    ]


async def test_concurrent_shared_burst_identity_and_page_collapse():
    """The headline: 8 concurrent same-prefix requests are token-
    identical to the private-copy baseline while resident pages
    collapse >= 4x (shared prefix attached once, pending-fill sharing
    included — every request is admitted before the first finishes)."""
    rs = np.random.RandomState(7)
    prompts = _prefix_prompts(8, 16 * PS, 4, rs)
    shared_eng = make_engine(sharing=True, slots=8, pages=8 * 20 + 16)
    private_eng = make_engine(sharing=False, slots=8, pages=8 * 20 + 16)
    shared_eng.start()
    private_eng.start()
    try:
        want = await asyncio.gather(
            *[run_req(private_eng, p, n=4) for p in prompts]
        )
        private_peak = private_eng.kv.peak_active_pages
        got = await asyncio.gather(
            *[run_req(shared_eng, p, n=4) for p in prompts]
        )
        shared_peak = shared_eng.kv.peak_active_pages
        assert got == want
        assert shared_eng.kv.prefix_hits["shared"] > 0
        assert shared_eng.kv.peak_shared_pages >= 16
        # >= 4x fewer resident pages than the private-copy baseline.
        assert shared_peak * 4 <= private_peak, (shared_peak, private_peak)
    finally:
        shared_eng.stop()
        private_eng.stop()


async def test_seeded_and_penalized_identity_with_sharing():
    """Sampled decode over shared pages equals private-copy decode:
    counter-based sampling keys on absolute position, not page
    identity."""
    rs = np.random.RandomState(11)
    prompts = _prefix_prompts(3, 4 * PS, 3, rs)
    kwargs = [
        dict(seed=101, temperature=0.9),
        dict(seed=202, temperature=0.8, freq_pen=0.5),
        dict(),  # greedy rides in the same batch
    ]
    shared_eng = make_engine(sharing=True)
    private_eng = make_engine(sharing=False)
    shared_eng.start()
    private_eng.start()
    try:
        want = [
            await run_req(private_eng, p, n=5, **kw)
            for p, kw in zip(prompts, kwargs)
        ]
        got = await asyncio.gather(
            *[
                run_req(shared_eng, p, n=5, **kw)
                for p, kw in zip(prompts, kwargs)
            ]
        )
        assert list(got) == want
    finally:
        shared_eng.stop()
        private_eng.stop()


async def test_spec_on_identity_with_sharing():
    """Speculative decoding over shared prefix pages stays token-
    identical to plain private-copy decode (repetitive prompts so the
    n-gram drafter actually engages)."""
    rs = np.random.RandomState(13)
    block = rs.randint(3, 200, size=8).tolist()
    prefix = (block * (2 * PS // 8 + 1))[: 2 * PS]
    prompts = [prefix + rs.randint(3, 200, size=2).tolist() for _ in range(3)]
    spec_eng = make_engine(sharing=True, spec="ngram")
    private_eng = make_engine(sharing=False)
    spec_eng.start()
    private_eng.start()
    try:
        want = [await run_req(private_eng, p, n=6) for p in prompts]
        got = await asyncio.gather(
            *[run_req(spec_eng, p, n=6) for p in prompts]
        )
        assert list(got) == want
    finally:
        spec_eng.stop()
        private_eng.stop()


async def test_full_cover_readmission_identity():
    """A page-aligned prompt whose every block is resident: the old
    trim re-prefilled a full page; now everything attaches and only the
    last token recomputes — token-identically."""
    rs = np.random.RandomState(17)
    prompt = rs.randint(3, 200, size=3 * PS).tolist()
    eng = make_engine(sharing=True)
    eng.start()
    try:
        first = await run_req(eng, prompt, n=5)
        hits0 = eng.kv.prefix_hits["shared"]
        again = await run_req(eng, prompt, n=5)
        assert again == first
        assert eng.kv.prefix_hits["shared"] >= hits0 + 3
    finally:
        eng.stop()


async def test_partial_tail_cow_engine_identity():
    """B's prompt ends inside a block A registered: B attaches A's page
    as a shared tail, COWs it before its first decode write (A is still
    decoding — a real divergent-write hazard), and emits exactly the
    private-copy tokens."""
    rs = np.random.RandomState(19)
    base = rs.randint(3, 200, size=2 * PS).tolist()
    short = base[: PS + 4]  # ends inside A's second block
    eng = make_engine(sharing=True)
    oracle = make_engine(sharing=False)
    eng.start()
    oracle.start()
    try:
        want_a = asyncio.ensure_future(run_req(eng, base, n=24))
        # Let A's prefill register its blocks before B is admitted.
        for _ in range(200):
            await asyncio.sleep(0.02)
            if eng.kv.match_prefix(base)[0]:
                break
        got_b = await run_req(eng, short, n=5)
        want_b = await run_req(oracle, short, n=5)
        await want_a
        assert got_b == want_b
        assert eng.kv.cow_copies >= 1
    finally:
        eng.stop()
        oracle.stop()


async def test_preempt_resume_identity_with_shared_prefix():
    """KV-pressure preemption with sharing on: same-prefix requests on
    a pressure-sized pool resume token-identically to an ample-pool
    run (the continuation re-attaches its own parked pages)."""
    rs = np.random.RandomState(23)
    prompts = _prefix_prompts(3, 2 * PS, 2, rs)
    ample = make_engine(sharing=True, pages=96)
    tight = make_engine(
        sharing=True, pages=14, preempt_stall_grace_s=0.05
    )
    ample.start()
    tight.start()
    try:
        want = await asyncio.gather(
            *[run_req(ample, p, n=12, seed=31 + i, temperature=0.7)
              for i, p in enumerate(prompts)]
        )
        got = await asyncio.gather(
            *[run_req(tight, p, n=12, seed=31 + i, temperature=0.7)
              for i, p in enumerate(prompts)]
        )
        assert list(got) == list(want)
    finally:
        ample.stop()
        tight.stop()


async def test_aggregate_context_twenty_x_pool():
    """The [scale] target: a shared-system-prompt fleet mix whose
    aggregate context is >= 20x the page pool completes with zero
    preemptions — impossible with private copies (one request's pages
    alone are ~7/8 of the pool)."""
    rs = np.random.RandomState(29)
    pool_pages = 28
    prefix = rs.randint(3, 200, size=20 * PS).tolist()  # 20 of 28 pages
    n_req = 28
    prompts = [
        prefix + rs.randint(3, 200, size=2).tolist() for _ in range(n_req)
    ]
    eng = make_engine(
        sharing=True, slots=4, pages=pool_pages, decode_window=4
    )
    eng.start()
    try:
        outs = await asyncio.gather(
            *[run_req(eng, p, n=2) for p in prompts]
        )
        assert all(len(o) == 2 for o in outs)
        aggregate_tokens = sum(len(p) + 2 for p in prompts)
        assert aggregate_tokens >= 20 * pool_pages * PS
        assert eng.preempted == 0
        assert eng.kv.peak_active_pages <= pool_pages
        assert eng.kv.peak_shared_pages >= 20
        # Every request past the first attached the 20 prefix pages.
        assert eng.kv.prefix_hits["shared"] >= 20 * (n_req - 1)
    finally:
        eng.stop()


# ----------------------------------------------------------------- disagg
async def test_disagg_suffix_only_transfer():
    """When the decode side already holds the shared prefix, the wire
    (and the extract gather) carries only the unshared suffix — and the
    stream is still token-identical to a local run."""
    from dynamo_exp_tpu.disagg import (
        DisaggConfig,
        DisaggConfigWatcher,
        DisaggDecodeEngine,
        KvPageReceiver,
        PrefillWorker,
    )
    from dynamo_exp_tpu.runtime.runtime import CancellationToken
    from dynamo_exp_tpu.runtime.transports.inproc import (
        InProcDiscovery,
        InProcWorkQueue,
    )

    def disagg_engine():
        cfg = EngineConfig(
            model=TINY,
            max_decode_slots=2,
            page_size=PS,
            num_pages=64,
            max_model_len=128,
            eos_token_ids=[],
            kv_dtype="float32",  # bit-exact transfer assertions
        )
        return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)

    prefill_eng = disagg_engine()
    decode_eng = disagg_engine()
    local_eng = disagg_engine()
    queue = InProcWorkQueue()
    recv = KvPageReceiver()
    await recv.start()
    cancel = CancellationToken()
    worker = PrefillWorker(prefill_eng, queue, cancel)
    worker_task = asyncio.ensure_future(worker.run())
    disc = InProcDiscovery()
    watcher = DisaggConfigWatcher(
        disc, "m", default=DisaggConfig(max_local_prefill_length=0)
    )
    disagg = DisaggDecodeEngine(decode_eng, queue, recv, watcher)
    try:
        rs = np.random.RandomState(37)
        prefix = rs.randint(3, 200, size=3 * PS).tolist()
        # Warm the DECODE side so the prefix is resident there.
        await run_req(decode_eng, prefix + [5], n=2)
        prompt = prefix + rs.randint(3, 200, size=PS + 4).tolist()
        want = await run_req(local_eng, prompt, n=8)
        moves0 = prefill_eng.metrics()["kv_page_moves"]
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 8
        b.stop_conditions.ignore_eos = True
        stream = await disagg.generate(b.to_dict())
        got = []
        async for item in stream:
            got.extend(item.get("token_ids", []))
        assert got == want
        assert disagg.remote_prefills == 1
        assert disagg.blocks_skipped == 3  # the resident prefix pages
        # The extract gather moved only the suffix pages (5 total - 3).
        assert prefill_eng.metrics()["kv_page_moves"] - moves0 == 2
        # No leaked pin: the decode pool quiesces back to zero refs.
        for _ in range(100):
            if decode_eng.kv.active_leases == 0:
                break
            await asyncio.sleep(0.02)
        assert decode_eng.kv.active_leases == 0
    finally:
        cancel.cancel()
        await asyncio.wait_for(worker_task, 5)
        await recv.close()
        for e in (prefill_eng, decode_eng, local_eng):
            e.stop()


# -------------------------------------------------------------------- sim
def test_sim_prefix_sharing_collapses_pages_and_counts_cow():
    from dynamo_exp_tpu.sim import ClusterSim, SimConfig
    from dynamo_exp_tpu.sim.workload import SimRequest

    def workload(prompt_lens):
        return [
            SimRequest(
                index=i, arrival_s=0.01 * i, prompt_len=pl, max_tokens=4,
                prefix_group=0, prefix_len=160,
            )
            for i, pl in enumerate(prompt_lens)
        ]

    cfg = dict(
        slots_per_instance=8, pages_per_instance=64, page_size=16,
        initial_instances=1, max_inflight=64,
    )
    # 8 same-group requests: shared attaches collapse pool usage.
    shared = ClusterSim(
        SimConfig(seed=1, prefix_sharing=True, **cfg),
        workload([168] * 8),
    ).run()
    private = ClusterSim(
        SimConfig(seed=1, prefix_sharing=False, **cfg),
        workload([168] * 8),
    ).run()
    assert shared.completed == private.completed == 8
    assert shared.shared_attached_pages >= 10 * 7  # later 7 reuse
    assert shared.shared_pages_peak >= 10
    assert private.shared_attached_pages == 0
    # COW: a member whose prompt sits fully inside the group prefix
    # (partial tail) after a longer member registered its blocks.
    cow = ClusterSim(
        SimConfig(seed=2, prefix_sharing=True, **cfg),
        workload([168, 100]),
    ).run()
    assert cow.cow_copies == 1
    assert cow.completed == 2


def test_sim_router_prefers_prefix_resident_instance():
    from dynamo_exp_tpu.sim import ClusterSim, SimConfig
    from dynamo_exp_tpu.sim.workload import SimRequest

    reqs = [
        SimRequest(index=i, arrival_s=0.05 * i, prompt_len=96,
                   max_tokens=4, prefix_group=7, prefix_len=96)
        for i in range(6)
    ]
    sim = ClusterSim(
        SimConfig(
            seed=3, slots_per_instance=8, pages_per_instance=128,
            page_size=16, initial_instances=3, max_inflight=64,
            prefix_sharing=True,
        ),
        reqs,
    )
    report = sim.run()
    assert report.completed == 6
    # Real index coverage steers the whole group onto one instance.
    resident = [
        i for i in sim.instances.values() if i.prefix_index.num_blocks
    ]
    assert len(resident) == 1


def test_sim_live_calibration_prefix_counters():
    """Sim vs live on the same scripted shape: one long member
    registers the shared prefix, one short member partial-tail-attaches
    (COW) — shared-attach and COW counts must agree exactly."""
    eng = make_engine(sharing=True, slots=2, pages=64)
    eng.start()
    try:
        rs = np.random.RandomState(41)
        base = rs.randint(3, 200, size=2 * PS).tolist()

        async def drive():
            long = asyncio.ensure_future(run_req(eng, base, n=24))
            for _ in range(200):
                await asyncio.sleep(0.02)
                if eng.kv.match_prefix(base)[0]:
                    break
            await run_req(eng, base[: PS + 4], n=4)
            await long

        asyncio.run(drive())
        live_shared = eng.kv.prefix_hits["shared"]
        live_cow = eng.kv.cow_copies
    finally:
        eng.stop()

    from dynamo_exp_tpu.sim import ClusterSim, SimConfig
    from dynamo_exp_tpu.sim.workload import SimRequest

    report = ClusterSim(
        SimConfig(
            seed=5, slots_per_instance=2, pages_per_instance=64,
            page_size=PS, initial_instances=1, max_inflight=16,
            prefix_sharing=True,
        ),
        [
            SimRequest(index=0, arrival_s=0.0, prompt_len=2 * PS,
                       max_tokens=24, prefix_group=0, prefix_len=2 * PS),
            SimRequest(index=1, arrival_s=1.0, prompt_len=PS + 4,
                       max_tokens=4, prefix_group=0, prefix_len=2 * PS),
        ],
    ).run()
    # Live: the short member attached 1 full block + the shared tail
    # (2 shared hits) and COWed once. Sim: identical counts.
    assert report.cow_copies == live_cow == 1
    assert report.shared_attached_pages == live_shared == 2


# ------------------------------------------------------------------ router
def test_router_index_recovers_coverage_after_reinsert():
    from dynamo_exp_tpu.kv_router.indexer import RadixIndex
    from dynamo_exp_tpu.kv_router.protocols import (
        KvCacheEventData,
        RouterEvent,
    )

    idx = RadixIndex()
    toks = list(range(1, 33))
    hashes = compute_block_hashes_for_seq(toks, 8)
    parent = None
    for h in hashes:
        idx.apply_event(
            RouterEvent(1, KvCacheEventData("stored", [h], parent))
        )
        parent = h
    assert idx.find_matches(hashes).scores == {1: 4}
    # Mid-chain eviction detaches (score drops to the hole) ...
    idx.apply_event(RouterEvent(1, KvCacheEventData("removed", [hashes[1]])))
    assert idx.find_matches(hashes).scores == {1: 1}
    # ... and re-registration restores FULL coverage (orphan re-attach;
    # the flat map this replaced could do no better than re-learn
    # blocks one event at a time — here the suffix was never lost).
    idx.apply_event(
        RouterEvent(1, KvCacheEventData("stored", [hashes[1]], hashes[0]))
    )
    assert idx.find_matches(hashes).scores == {1: 4}
