"""AOT variant precompilation and warm-boot provisioning (docs/aot.md).

A cold engine pays the whole compiled-variant lattice in first-traffic
compiles — PR 8's compile attribution showed that delay dominating
scale-up, and the planner models it as ``SloTargets.provision_s``. This
package makes the lattice a *build artifact* instead of a first-traffic
tax:

- :mod:`.lattice` enumerates the full compile lattice offline from an
  :class:`~dynamo_exp_tpu.engine.EngineConfig` as a deterministic,
  hashable :class:`CompileManifest` — sharing the variant-key function
  (:func:`resolve_ragged_key`) with the engine's ``_ragged_fn``, so the
  manifest can never drift from what the loop actually dispatches.
- :mod:`.compile` AOT-lowers and compiles every manifest entry
  (``.lower().compile()`` with the engine's explicit shardings) and
  wires the JAX persistent compilation cache, so a second process loads
  serialized executables instead of recompiling.
- :mod:`.warmup` is ``TPUEngine.prewarm``'s implementation: populate
  the engine's ``_ragged_fns`` (and the gather/scatter/COW kernels)
  from the cache *before* the engine accepts traffic, and seed the
  dispatch profiler's freshness state so steady-state compile-miss
  flatness holds from the very first dispatch.

Operator surface: ``llmctl aot compile|list|warm|smoke``,
``dynamo_exp_tpu.run --prewarm --compile-cache-dir``, and the
``DYN_COMPILE_CACHE`` environment variable.
"""

from .compile import (
    aot_compile,
    cache_dir_from_env,
    enable_persistent_cache,
    manifest_for_engine,
)
from .lattice import (
    CompileManifest,
    RaggedVariant,
    build_manifest,
    mixed_token_buckets,
    page_bound_buckets,
    page_move_buckets,
    ragged_variants,
    resolve_ragged_key,
    windowed_token_buckets,
)
from .warmup import PrewarmReport, prewarm_engine

__all__ = [
    "CompileManifest",
    "PrewarmReport",
    "RaggedVariant",
    "aot_compile",
    "build_manifest",
    "cache_dir_from_env",
    "enable_persistent_cache",
    "manifest_for_engine",
    "mixed_token_buckets",
    "page_bound_buckets",
    "page_move_buckets",
    "prewarm_engine",
    "ragged_variants",
    "resolve_ragged_key",
    "windowed_token_buckets",
]
