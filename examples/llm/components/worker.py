"""TpuWorker: the engine service of the flagship graphs.

Reference parity: ``/root/reference/examples/llm/components/worker.py``
(VllmWorker: engine behind a ``generate`` endpoint, KV events, load
metrics, optional remote-prefill offload decision). TPU-native: the
in-process continuous-batching engine, configured through ServiceConfig.
"""

from __future__ import annotations

import asyncio
import logging

from dynamo_exp_tpu.sdk import (
    async_on_start,
    dynamo_context,
    endpoint,
    service,
    stats_handler,
)

logger = logging.getLogger(__name__)


@service(dynamo={"namespace": "dynamo"}, resources={"tpu": 1})
class TpuWorker:
    """Decode (or aggregated) engine worker."""

    # ServiceConfig-overridable (configs/*.yaml).
    model_path: str = ""
    served_model_name: str = ""
    random_weights: bool = False
    max_decode_slots: int = 8
    page_size: int = 16
    num_pages: int = 0  # 0 = auto
    max_model_len: int = 2048
    kv_dtype: str = "bfloat16"
    # "none" = aggregated; "decode" = offload long prefills to the
    # prefill fleet through the work queue + KV transfer plane.
    disagg_mode: str = "none"
    max_local_prefill_length: int = 1000
    # Speculative decoding (docs/speculative.md): "off" | drafter name.
    spec: str = "off"
    spec_draft_len: int = 4
    spec_max_draft: int = 8
    spec_ngram: int = 3

    def __init__(self):
        self.engine = None
        self.serving = None
        self._kv_pub = None
        self._receiver = None
        self._watcher = None

    @async_on_start
    async def start_engine(self) -> None:
        from dynamo_exp_tpu.local_model import register_llm
        from dynamo_exp_tpu.models.hub import resolve_model_path
        from dynamo_exp_tpu.run import build_tpu_engine

        drt = dynamo_context["runtime"]
        component = dynamo_context["component"]

        class _Opts:  # the CLI's engine builder, driven by ServiceConfig
            model_path = resolve_model_path(self.model_path)
            model_name = self.served_model_name
            preset = ""
            random_weights = self.random_weights
            page_size = self.page_size
            num_pages = self.num_pages
            max_decode_slots = self.max_decode_slots
            max_model_len = self.max_model_len
            kv_dtype = self.kv_dtype
            host_cache_pages = 0
            max_tokens = 256
            tp = 1
            spec = self.spec
            spec_draft_len = self.spec_draft_len
            spec_max_draft = self.spec_max_draft
            spec_ngram = self.spec_ngram

        self.engine, mdc = build_tpu_engine(_Opts)
        self.engine.start()
        self.serving = self.engine
        if self.disagg_mode == "decode":
            from dynamo_exp_tpu.disagg import (
                DisaggConfig,
                DisaggConfigWatcher,
                DisaggDecodeEngine,
                KvPageReceiver,
            )
            from dynamo_exp_tpu.planner.planner import prefill_queue_name

            self._receiver = KvPageReceiver()
            await self._receiver.start()
            self._watcher = DisaggConfigWatcher(
                drt.discovery,
                mdc.display_name if mdc else "model",
                default=DisaggConfig(
                    max_local_prefill_length=self.max_local_prefill_length
                ),
            )
            await self._watcher.start()
            queue = drt.work_queue(
                prefill_queue_name(self.served_model_name or "model")
            )
            self.serving = DisaggDecodeEngine(
                self.engine, queue, self._receiver, self._watcher
            )
        if mdc is not None:
            await register_llm(
                drt,
                component.endpoint("generate"),
                self.model_path,
                self.served_model_name or None,
                kv_cache_block_size=self.page_size,
            )
        # KV events → the router index (kv routing mode). The endpoint
        # instance id only exists once serving starts (after this hook),
        # so wire the publisher from a deferred task.
        from dynamo_exp_tpu.kv_router.publisher import KvEventPublisher

        loop = asyncio.get_running_loop()

        async def wire_kv_events():
            for _ in range(200):
                iid = dynamo_context["instance_ids"].get("generate")
                if iid is not None:
                    self._kv_pub = KvEventPublisher(
                        drt.event_plane, component.path, iid, loop
                    )
                    self.engine.kv.event_cb = self._kv_pub.engine_callback()
                    return
                await asyncio.sleep(0.05)
            logger.warning("generate endpoint never served; no KV events")

        self._kv_task = asyncio.ensure_future(wire_kv_events())

    @endpoint()
    async def generate(self, request: dict):
        stream = await self.serving.generate(request)
        async for item in stream:
            yield item

    @stats_handler
    def stats(self) -> dict:
        return self.engine.metrics() if self.engine else {}
