"""Per-process service entry: run ONE service of a graph.

Reference parity: ``deploy/dynamo/sdk/cli/serve_dynamo.py:120-367`` —
each circus watcher runs this module for its service: build the
DistributedRuntime, create the component, resolve ``depends()`` edges,
run ``@async_on_start`` hooks, then serve every ``@endpoint``.

    python -m dynamo_exp_tpu.sdk.serve_service pkg.module:RootClass \
        --service-name Middle [--config cfg.yaml]
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import importlib
import logging
import signal
import sys

logger = logging.getLogger("dynamo_exp_tpu.sdk.serve_service")


def load_target(target: str) -> type:
    mod_name, _, cls_name = target.partition(":")
    if not cls_name:
        raise SystemExit(f"target must be module:Class, got {target!r}")
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name)


async def run_service(
    target: str,
    service_name: str | None,
    config_path: str | None,
    multihost=None,
):
    from ..runtime.component import DistributedRuntime
    from ..runtime.engine import AsyncEngineContext
    from ..runtime.annotated import Annotated
    from .config import ServiceConfig
    from .dependency import depends as depends_t
    from .service import discover_graph, dynamo_context

    root = load_target(target)
    graph = discover_graph(root)
    spec = next(
        (s for s in graph if s.name == (service_name or graph[-1].name)), None
    )
    if spec is None:
        raise SystemExit(
            f"service {service_name!r} not in graph "
            f"({[s.name for s in graph]})"
        )

    drt = DistributedRuntime.from_settings()
    if multihost is not None and multihost.is_multi_node:
        # This worker owns the TPU for its host rank: join the global
        # JAX runtime before anything touches a device (supervisor
        # forwards the flags; reference capability: ray.rs:66-107).
        from ..parallel.multihost import bringup

        await bringup(multihost, discovery=drt.discovery)
    component = drt.namespace(spec.namespace).component(spec.component_name)
    dynamo_context.update(
        runtime=drt,
        namespace=spec.namespace,
        component=component,
        endpoints=sorted(spec.endpoints),
        instance_ids={},
    )

    instance = spec.cls()
    ServiceConfig.load(config_path).apply_to(instance, spec.name)

    # Resolve graph edges to live clients before user startup hooks run.
    for dep in vars(spec.cls).values():
        if isinstance(dep, depends_t):
            await dep.resolve(drt)
    for hook in spec.on_start:
        await getattr(instance, hook)()

    stats = (
        getattr(instance, spec.stats_method) if spec.stats_method else None
    )
    served = []
    for ep_name in sorted(spec.endpoints):
        bound = getattr(instance, spec.endpoints[ep_name].__name__)

        def make_handler(fn):
            async def handler(request: dict, context: AsyncEngineContext):
                try:
                    async for item in fn(request):
                        yield Annotated.from_data(item).to_dict()
                except Exception as e:  # error frames travel in-band
                    logger.exception("endpoint handler failed")
                    yield Annotated.from_error(str(e)).to_dict()

            return handler

        s = await component.endpoint(ep_name).serve_endpoint(
            make_handler(bound), stats_handler=stats
        )
        dynamo_context["instance_ids"][ep_name] = s.instance_id
        served.append(s)

    print(f"service {spec.name} ready ({len(served)} endpoints)", flush=True)
    try:
        await drt.runtime.primary_token.cancelled()
    finally:
        for s in served:
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(s.close(), 15)
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(drt.close(), 15)


def main(argv: list[str] | None = None) -> None:
    # DYN_LOG / DYN_LOGGING_JSONL aware (trace-correlated JSONL lines);
    # service processes inherit DYN_TRACE_FILE for span recording.
    from ..runtime.logging import configure_logging

    configure_logging()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("target", help="pkg.module:RootClass")
    p.add_argument("--service-name", default=None)
    p.add_argument("--config", default=None)
    p.add_argument("--num-nodes", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--dist-leader", default="")
    p.add_argument("--dist-port", type=int, default=9911)
    p.add_argument("--deployment", default="default")
    args = p.parse_args(argv if argv is not None else sys.argv[1:])

    multihost = None
    if args.num_nodes > 1:
        from ..parallel.multihost import MultiNodeConfig

        multihost = MultiNodeConfig(
            num_nodes=args.num_nodes,
            node_rank=args.node_rank,
            leader_addr=args.dist_leader or None,
            dist_port=args.dist_port,
            deployment=args.deployment,
        )

    loop = asyncio.new_event_loop()
    task = loop.create_task(
        run_service(args.target, args.service_name, args.config, multihost)
    )
    for sig in (signal.SIGINT, signal.SIGTERM):
        with contextlib.suppress(NotImplementedError, ValueError):
            loop.add_signal_handler(sig, task.cancel)
    try:
        loop.run_until_complete(task)
    except asyncio.CancelledError:
        pass
    finally:
        loop.close()


if __name__ == "__main__":
    main()
