"""In-process transport: discovery + request plane with zero network.

This is "static mode" (reference:
``DistributedRuntime::from_settings_without_discovery``,
``/root/reference/lib/runtime/src/distributed.rs:83-86``) plus the
in-memory mock-network test substrate
(``lib/runtime/tests/common/mock.rs``): the full component/endpoint/router
stack runs inside one process, optionally with injectable latency for
multi-node simulation in tests.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import random
import weakref
from dataclasses import dataclass
from typing import AsyncIterator

from ..engine import AsyncEngineContext
from .base import (
    Discovery,
    EventPlane,
    Handler,
    InstanceInfo,
    Lease,
    ObjectStore,
    RequestPlane,
    ServedEndpoint,
    StatsHandler,
    WorkQueue,
)

_instance_ids = itertools.count(1)


def next_instance_id() -> int:
    return next(_instance_ids)


@dataclass
class LatencyModel:
    """Injectable request/response latency for simulated multi-node tests."""

    constant_ms: float = 0.0
    jitter_ms: float = 0.0

    async def delay(self) -> None:
        ms = self.constant_ms + (random.random() * self.jitter_ms)
        if ms > 0:
            await asyncio.sleep(ms / 1000.0)


class InProcLease(Lease):
    def __init__(self, discovery: "InProcDiscovery", lease_id: int):
        self._discovery = discovery
        self._id = lease_id
        self._valid = True

    @property
    def lease_id(self) -> int:
        return self._id

    def is_valid(self) -> bool:
        return self._valid

    async def revoke(self) -> None:
        if self._valid:
            self._valid = False
            await self._discovery._revoke_lease(self._id)


class InProcDiscovery(Discovery):
    """Registry + KV store living in process memory, with watches."""

    def __init__(self):
        self._instances: dict[int, InstanceInfo] = {}
        self._kv: dict[str, bytes] = {}
        self._lease_keys: dict[int, set[str]] = {}
        self._lease_instances: dict[int, set[int]] = {}
        self._change = asyncio.Condition()
        self._version = 0

    async def _bump(self) -> None:
        async with self._change:
            self._version += 1
            self._change.notify_all()

    async def create_lease(self, ttl_s: float | None = None) -> Lease:
        lease = InProcLease(self, next_instance_id())
        self._lease_keys.setdefault(lease.lease_id, set())
        return lease

    async def register_instance(
        self, info: InstanceInfo, lease: Lease | None = None
    ) -> Lease:
        if lease is None:
            lease = await self.create_lease()
        self._instances[info.instance_id] = info
        self._lease_instances.setdefault(lease.lease_id, set()).add(info.instance_id)
        await self._bump()
        return lease

    async def deregister_instance(self, instance_id: int) -> None:
        self._instances.pop(instance_id, None)
        for insts in self._lease_instances.values():
            insts.discard(instance_id)
        await self._bump()

    async def _revoke_lease(self, lease_id: int) -> None:
        for inst in self._lease_instances.pop(lease_id, set()):
            self._instances.pop(inst, None)
        for key in self._lease_keys.pop(lease_id, set()):
            self._kv.pop(key, None)
        await self._bump()

    async def list_instances(self, prefix: str) -> list[InstanceInfo]:
        return [
            i for i in self._instances.values() if i.address.path.startswith(prefix)
        ]

    async def watch_instances(self, prefix: str) -> AsyncIterator[list[InstanceInfo]]:
        last = -1
        while True:
            async with self._change:
                if self._version == last:
                    await self._change.wait()
                last = self._version
            yield await self.list_instances(prefix)

    async def kv_put(self, key: str, value: bytes, lease: Lease | None = None) -> None:
        self._kv[key] = value
        if lease is not None:
            self._lease_keys.setdefault(lease.lease_id, set()).add(key)
        await self._bump()

    async def kv_create(
        self, key: str, value: bytes, lease: Lease | None = None
    ) -> bool:
        if key in self._kv:
            return False
        await self.kv_put(key, value, lease)
        return True

    async def kv_get(self, key: str) -> bytes | None:
        return self._kv.get(key)

    async def kv_get_prefix(self, prefix: str) -> dict[str, bytes]:
        return {k: v for k, v in self._kv.items() if k.startswith(prefix)}

    async def kv_delete(self, key: str) -> None:
        self._kv.pop(key, None)
        await self._bump()

    async def kv_watch_prefix(self, prefix: str) -> AsyncIterator[dict[str, bytes]]:
        last = -1
        while True:
            async with self._change:
                if self._version == last:
                    await self._change.wait()
                last = self._version
            yield await self.kv_get_prefix(prefix)


class _InProcServed(ServedEndpoint):
    def __init__(self, plane: "InProcRequestPlane", instance_id: int):
        self._plane = plane
        self._instance_id = instance_id

    async def close(self) -> None:
        entry = self._plane._handlers.pop(self._instance_id, None)
        if entry is not None:
            # Graceful drain: wait for inflight requests to finish.
            _, _, inflight = entry
            while inflight[0] > 0:
                await asyncio.sleep(0.005)


class InProcRequestPlane(RequestPlane):
    def __init__(self, latency: LatencyModel | None = None):
        self._handlers: dict[int, tuple[Handler, StatsHandler | None, list[int]]] = {}
        self.latency = latency or LatencyModel()

    async def serve(
        self,
        info: InstanceInfo,
        handler: Handler,
        stats_handler: StatsHandler | None = None,
    ) -> ServedEndpoint:
        self._handlers[info.instance_id] = (handler, stats_handler, [0])
        return _InProcServed(self, info.instance_id)

    async def request_stream(
        self,
        instance: InstanceInfo,
        request: dict,
        context: AsyncEngineContext,
    ) -> AsyncIterator[dict]:
        entry = self._handlers.get(instance.instance_id)
        if entry is None:
            raise ConnectionError(
                f"no served endpoint for instance {instance.instance_id}"
            )
        handler, _, inflight = entry
        await self.latency.delay()
        if context.deadline_expired:
            # Parity with the TCP plane: an expired request is refused
            # in-band before the handler runs.
            from ...telemetry import get_telemetry

            get_telemetry().deadline_exceeded.labels("request_plane").inc()

            async def _expired() -> AsyncIterator[dict]:
                yield {
                    "event": "error",
                    "comment": [f"deadline exceeded for request {context.id}"],
                }

            return _expired()

        # Count the request as inflight from dispatch (not first iteration),
        # so graceful drain can't miss a just-dispatched request.
        inflight[0] += 1
        done = [False]

        def _finish() -> None:
            if not done[0]:
                done[0] = True
                inflight[0] -= 1

        async def _gen() -> AsyncIterator[dict]:
            try:
                agen = handler(request, context)
                async for frame in agen:
                    if context.is_killed:
                        with contextlib.suppress(Exception):
                            await agen.aclose()
                        break
                    await self.latency.delay()
                    yield frame
            finally:
                _finish()

        gen = _gen()
        # Fallback: if the caller drops the stream without ever iterating,
        # the generator's finally never runs; decrement on GC instead.
        weakref.finalize(gen, _finish)
        return gen

    async def scrape_stats(self, instance: InstanceInfo) -> dict:
        entry = self._handlers.get(instance.instance_id)
        if entry is None:
            raise ConnectionError(f"instance {instance.instance_id} gone")
        _, stats_handler, inflight = entry
        stats = {"inflight": inflight[0]}
        if stats_handler is not None:
            stats.update(stats_handler())
        return stats


class InProcEventPlane(EventPlane):
    """Subject-based fan-out pub/sub inside one process. Subjects support
    a trailing ``*`` wildcard on subscribe (``ns.comp.*``)."""

    def __init__(self, latency: LatencyModel | None = None):
        self.latency = latency or LatencyModel()
        self._subs: dict[str, list[asyncio.Queue]] = {}

    async def publish(self, subject: str, payload: dict) -> None:
        await self.latency.delay()
        for pattern, queues in list(self._subs.items()):
            if pattern == subject or (
                pattern.endswith("*") and subject.startswith(pattern[:-1])
            ):
                for q in queues:
                    q.put_nowait(payload)

    async def subscribe(self, subject: str) -> AsyncIterator[dict]:
        # Register the queue before returning so events published between
        # subscribe() and the consumer's first await are not lost.
        q: asyncio.Queue = asyncio.Queue()
        self._subs.setdefault(subject, []).append(q)

        async def _gen() -> AsyncIterator[dict]:
            try:
                while True:
                    yield await q.get()
            finally:
                with contextlib.suppress(ValueError):
                    self._subs.get(subject, []).remove(q)

        return _gen()


class InProcWorkQueue(WorkQueue):
    """FIFO queue in process memory (static-mode prefill queue)."""

    def __init__(self):
        self._q: asyncio.Queue[bytes] = asyncio.Queue()

    async def push(self, payload: bytes) -> None:
        self._q.put_nowait(payload)

    async def pull(self, timeout_s: float | None = None) -> bytes | None:
        try:
            if timeout_s is None:
                return await self._q.get()
            return await asyncio.wait_for(self._q.get(), timeout_s)
        except asyncio.TimeoutError:
            return None

    async def size(self) -> int:
        return self._q.qsize()


class InProcObjectStore(ObjectStore):
    def __init__(self):
        self._buckets: dict[str, dict[str, bytes]] = {}

    async def put(self, bucket: str, key: str, data: bytes) -> None:
        self._buckets.setdefault(bucket, {})[key] = data

    async def get(self, bucket: str, key: str) -> bytes | None:
        return self._buckets.get(bucket, {}).get(key)

    async def delete(self, bucket: str, key: str) -> None:
        self._buckets.get(bucket, {}).pop(key, None)

    async def list(self, bucket: str) -> list[str]:
        return sorted(self._buckets.get(bucket, {}))
