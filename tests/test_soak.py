"""Soak/lifecycle tier: sustained load under cancellation churn.

Reference capability anchors: ``lib/runtime/tests/soak.rs:1-160`` and
``lib/bindings/python/tests/soak.py`` — batches of streamed requests
pushed through the distributed runtime for a sustained period, every
response drained, nothing leaked. Here two layers get soaked on the CPU
mesh:

- the runtime plane (serve_endpoint → TCP request plane → client), a few
  thousand streams with mid-stream cancellations;
- the engine+router+disagg stack, hundreds of generations with
  cancellation churn, asserting the KV page pool returns to baseline
  (no page leak) and no receiver futures are left stuck.

Marked ``nightly`` (they run minutes, deliberately).
"""

import asyncio

import numpy as np
import pytest

pytestmark = pytest.mark.nightly


async def test_runtime_plane_soak_with_cancellation_churn():
    """Thousands of streams over the real TCP request plane; every 7th
    stream is dropped mid-flight. The plane must end with zero inflight
    handlers and the process with no stray tasks."""
    from dynamo_exp_tpu.runtime.component import DistributedRuntime
    from dynamo_exp_tpu.runtime.engine import AsyncEngineContext
    from dynamo_exp_tpu.runtime.transports.inproc import InProcDiscovery
    from dynamo_exp_tpu.runtime.transports.tcp import TcpRequestPlane

    drt = DistributedRuntime(
        discovery=InProcDiscovery(), request_plane=TcpRequestPlane()
    )

    async def handler(request, context):
        for i in range(request.get("n", 5)):
            if context.is_stopped:
                return
            yield {"i": i}
            await asyncio.sleep(0)

    ep = drt.namespace("soak").component("backend").endpoint("generate")
    served = await ep.serve_endpoint(handler)
    client = await ep.client()

    TOTAL, BATCH = 2000, 100
    done = cancelled = 0
    baseline_tasks = len(asyncio.all_tasks())
    for batch_start in range(0, TOTAL, BATCH):

        async def one(i):
            nonlocal done, cancelled
            ctx = AsyncEngineContext()
            stream = await client.generate_to(
                client.instances[0], {"n": 6}, ctx
            )
            seen = 0
            async for frame in stream:
                seen += 1
                if i % 7 == 0 and seen >= 2:
                    ctx.stop_generating()
                    cancelled += 1
                    break
            done += 1

        await asyncio.gather(
            *[one(batch_start + i) for i in range(BATCH)]
        )

    assert done == TOTAL and cancelled > 0
    # The plane's inflight counters must be fully drained — a leak in
    # per-request accounting would show up here after 2000 streams.
    await asyncio.sleep(0.1)
    assert all(
        inflight[0] == 0
        for _, _, inflight in drt.request_plane._handlers.values()
    )
    await served.close()
    await drt.close()
    # No unbounded task growth: everything spawned per-request is gone
    # (a small slack covers the transports' own long-lived tasks).
    await asyncio.sleep(0.1)
    assert len(asyncio.all_tasks()) <= baseline_tasks + 5


async def test_engine_disagg_soak_no_page_leak():
    """Hundreds of generations through engine+disagg under cancellation
    churn: the page pool must return to its post-warmup baseline (no
    leak) and the KV receiver must hold no stuck futures
    (soak.rs parity for the serving stack)."""
    from dynamo_exp_tpu.disagg import (
        DisaggConfig,
        DisaggConfigWatcher,
        DisaggDecodeEngine,
        KvPageReceiver,
        PrefillWorker,
    )
    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models import TINY
    from dynamo_exp_tpu.parallel import single_device_mesh
    from dynamo_exp_tpu.protocols.common import BackendInput
    from dynamo_exp_tpu.runtime.engine import AsyncEngineContext
    from dynamo_exp_tpu.runtime.runtime import CancellationToken
    from dynamo_exp_tpu.runtime.transports.inproc import (
        InProcDiscovery,
        InProcWorkQueue,
    )

    PS = 8

    def make_engine():
        return TPUEngine(
            EngineConfig(
                model=TINY,
                max_decode_slots=4,
                page_size=PS,
                num_pages=96,
                max_model_len=128,
                eos_token_ids=[],
                kv_dtype="float32",
            ),
            mesh=single_device_mesh(),
            seed=0,
        )

    prefill_eng = make_engine()
    decode_eng = make_engine()
    queue = InProcWorkQueue()
    recv = KvPageReceiver()
    await recv.start()
    cancel = CancellationToken()
    worker = PrefillWorker(prefill_eng, queue, cancel)
    worker_task = asyncio.ensure_future(worker.run())
    watcher = DisaggConfigWatcher(
        InProcDiscovery(),
        "m",
        # Long prompts prefill remotely, short ones locally — both paths
        # get churned.
        default=DisaggConfig(max_local_prefill_length=2 * PS),
    )
    disagg = DisaggDecodeEngine(decode_eng, queue, recv, watcher)

    rs = np.random.RandomState(0)

    async def one(i: int) -> None:
        # Mix of short (local prefill) and long (remote prefill) prompts.
        isl = int(rs.randint(4, 5 * PS))
        prompt = rs.randint(3, 200, size=isl).tolist()
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = int(rs.randint(2, 8))
        b.stop_conditions.ignore_eos = True
        ctx = AsyncEngineContext()
        stream = await disagg.generate(b.to_dict(), ctx)
        seen = 0
        async for item in stream:
            seen += len(item.get("token_ids", []))
            if i % 5 == 0 and seen >= 1:
                ctx.stop_generating()  # cancellation churn
        assert seen >= 1

    # Warmup compiles all bucket variants and seeds steady-state pools.
    await asyncio.gather(*[one(i + 1) for i in range(8)])

    TOTAL, BATCH = 200, 8
    for start in range(0, TOTAL, BATCH):
        await asyncio.gather(*[one(start + i) for i in range(BATCH)])

    try:
        # Pages are released asynchronously after the last frame. Once
        # every stream is drained, NO page may still hold a reference:
        # free_pages counts free + LRU-parked (reusable prefix blocks),
        # so active_pages > 0 here means a dead request leaked a ref.
        for _ in range(50):
            if (
                decode_eng.kv.active_pages == 0
                and prefill_eng.kv.active_pages == 0
            ):
                break
            await asyncio.sleep(0.1)
        assert decode_eng.kv.active_pages == 0
        assert prefill_eng.kv.active_pages == 0
        # Receiver: no stuck futures, no orphaned chunk callbacks.
        assert not recv._pending
        assert not recv._chunk_cbs
        assert disagg.remote_prefills > 0  # both paths actually exercised
        assert worker.served == disagg.remote_prefills
    finally:
        cancel.cancel()
        await asyncio.wait_for(worker_task, 5)
        await recv.close()
        prefill_eng.stop()
        decode_eng.stop()
