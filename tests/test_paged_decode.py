"""Cross-check the ragged Pallas decode kernel against the XLA oracle.

The kernel (``ops/paged_decode.py``) replaces what vLLM's PagedAttention
CUDA kernels gave the reference for free (SURVEY.md §2.9); its value is
correctness-critical DMA/online-softmax bookkeeping, so every behaviour
it promises is pinned here in interpreter mode on the CPU mesh:
ragged lengths, inactive rows, GQA grouping, non-contiguous page tables,
and the tp>1 shard_map dispatch used by ``models/llama.forward``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_exp_tpu.ops.attention import paged_attention
from dynamo_exp_tpu.ops.paged_decode import paged_decode_attention


def _setup(rng, B, H, Hkv, D, P, ps, pmax, lengths, dtype=jnp.float32):
    """Random pool + a scrambled page table; returns (q, k, v, table).
    Pools use the engine's fused-lane layout [P, ps, Hkv*D]."""
    ks = jax.random.split(jax.random.PRNGKey(rng), 3)
    q = jax.random.normal(ks[0], (B, H, D), dtype)
    k = jax.random.normal(ks[1], (P, ps, Hkv * D), dtype)
    v = jax.random.normal(ks[2], (P, ps, Hkv * D), dtype)
    # Assign each row distinct, non-contiguous pages so a kernel that
    # ignores the table (e.g. reads pages sequentially) fails loudly.
    perm = np.random.RandomState(rng).permutation(P)
    table = np.zeros((B, pmax), np.int32)
    used = 0
    for b, ln in enumerate(lengths):
        n = max(1, -(-ln // ps))
        table[b, :n] = perm[used : used + n]
        used += n
    return q, k, v, jnp.asarray(table)


def _oracle(q, k, v, table, lengths):
    """ops/attention.py path with per-row position masking, zeroing
    inactive rows the way the kernel promises to."""
    positions = jnp.asarray(lengths, jnp.int32)[:, None] - 1  # [B, 1]
    out = paged_attention(q[:, None], k, v, table, positions)[:, 0]
    active = (jnp.asarray(lengths) > 0)[:, None, None]
    return jnp.where(active, out, 0.0)


@pytest.mark.parametrize(
    "lengths",
    [
        [1, 17, 32, 5],  # ragged, page-boundary straddling
        [0, 40, 0, 3],  # inactive rows interleaved
        [64, 64, 64, 64],  # uniform full pages
    ],
)
def test_kernel_matches_oracle_ragged(lengths):
    B, H, Hkv, D, ps, pmax = 4, 8, 4, 64, 16, 8
    q, k, v, table = _setup(0, B, H, Hkv, D, 64, ps, pmax, lengths)
    lens = jnp.asarray(lengths, jnp.int32)
    got = paged_decode_attention(q, k, v, table, lens, interpret=True)
    want = _oracle(q, k, v, table, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_inactive_rows_exact_zero():
    lengths = [0, 9, 0, 0]
    q, k, v, table = _setup(1, 4, 4, 4, 32, 32, 8, 4, lengths)
    out = np.asarray(
        paged_decode_attention(
            q, k, v, table, jnp.asarray(lengths, jnp.int32), interpret=True
        )
    )
    assert (out[[0, 2, 3]] == 0.0).all()
    assert np.abs(out[1]).max() > 0.0


def test_gqa_grouping():
    # 8 query heads over 2 kv heads: groups must read their own kv head.
    lengths = [23, 7]
    q, k, v, table = _setup(2, 2, 8, 2, 32, 16, 8, 4, lengths)
    lens = jnp.asarray(lengths, jnp.int32)
    got = paged_decode_attention(q, k, v, table, lens, interpret=True)
    want = _oracle(q, k, v, table, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_bfloat16_cache():
    lengths = [19, 60, 1, 33]
    q, k, v, table = _setup(3, 4, 4, 4, 64, 32, 16, 4, lengths, jnp.bfloat16)
    lens = jnp.asarray(lengths, jnp.int32)
    got = paged_decode_attention(q, k, v, table, lens, interpret=True)
    want = _oracle(q, k, v, table, lengths)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=2e-2
    )


def test_tp_shard_map_dispatch():
    """The tp>1 path in models/llama._pallas_decode: heads sharded over
    the mesh, page pool kv-head-sharded, full tables replicated."""
    from dynamo_exp_tpu.models.llama import _pallas_decode
    from dynamo_exp_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(tp=4)
    lengths = [11, 0, 37, 25]
    q, k, v, table = _setup(4, 4, 8, 4, 64, 32, 16, 8, lengths)
    lens = jnp.asarray(lengths, jnp.int32)
    got = _pallas_decode(q, k, v, table, lens, 4, mesh, interpret=True)
    want = _oracle(q, k, v, table, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_engine_decodes_with_pallas_interpret(tiny_model_dir):
    """End-to-end: an engine configured with attention_impl=pallas +
    interpret produces the same greedy tokens as the XLA engine."""
    import asyncio

    from dynamo_exp_tpu.engine.config import EngineConfig
    from dynamo_exp_tpu.engine.engine import TPUEngine
    from dynamo_exp_tpu.models.config import ModelConfig

    mcfg = ModelConfig(
        num_layers=2,
        hidden_size=64,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=128,
        vocab_size=128,
        max_position_embeddings=256,
        dtype="float32",
    )

    def run(attention_impl):
        cfg = EngineConfig(
            model=mcfg,
            max_decode_slots=2,
            page_size=8,
            num_pages=64,
            max_model_len=128,
            attention_impl=attention_impl,
            pallas_interpret=attention_impl == "pallas",
            enable_kv_events=False,
        )
        eng = TPUEngine(cfg, seed=7)

        async def go():
            stream = await eng.generate(
                {
                    "token_ids": list(range(1, 20)),
                    "stop_conditions": {"max_tokens": 8},
                    "sampling_options": {"temperature": 0.0},
                }
            )
            toks = []
            async for out in stream:
                toks.extend(out.get("token_ids") or [])
            return toks

        try:
            return asyncio.run(asyncio.wait_for(go(), timeout=120))
        finally:
            eng.stop()

    assert run("pallas") == run("xla")
