"""Engine flight recorder + no-progress watchdog (docs/observability.md
"Engine flight recorder & watchdog").

When the engine loop wedges, spans and counters tell you nothing — the
request that hangs never finishes a stage. The flight recorder is the
black box for that case: a bounded in-memory ring of engine-loop events
(admission, dispatch/consume, stall start/end, preemption, lease
lifecycle, spec accept/rewind, chain breaks) that costs one deque
append per event while everything is healthy, and is dumped to JSONL —
with a scheduler/slot/page snapshot — exactly when something isn't:

- the **watchdog** thread detects no-progress-while-work-is-queued and
  dumps once per stall episode;
- **SIGUSR1** dumps every registered engine's ring on demand
  (``install_sigusr1`` / the ``dynamo_exp_tpu.run`` handler);
- an **engine-loop crash** dumps on the way out.

Event payloads are deterministic given a deterministic engine run (the
chaos suite proves bit-identical event sequences across same-seed
runs); only the per-event wall timestamp ``t`` differs between runs.
``llmctl flight <file>`` renders a dump as a per-slot timeline the way
``llmctl trace`` renders spans.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time

logger = logging.getLogger(__name__)


def default_dump_path() -> str:
    """``DYN_FLIGHT_DUMP`` or a per-process file under the tempdir."""
    return os.environ.get("DYN_FLIGHT_DUMP", "") or os.path.join(
        tempfile.gettempdir(), f"dynamo_flight_{os.getpid()}.jsonl"
    )


class FlightRecorder:
    """Bounded ring of engine-loop events.

    ``record`` is the hot-path call: one lock-guarded list append (the
    ring is a plain list + head index so ``seq`` numbering and eviction
    stay atomic). ``data`` must be JSON-serializable and — for the
    determinism guarantee — free of wall-clock values and run-global
    ids; the recorder adds ``seq`` and ``t`` itself.
    """

    def __init__(self, capacity: int = 2048):
        self.capacity = max(capacity, 16)
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._head = 0  # index of the oldest event once the ring wraps
        self.seq = 0  # total events ever recorded (watchdog progress)

    def record(self, kind: str, **data) -> None:
        ev = {"seq": 0, "t": time.time(), "kind": kind, **data}  # dynlint: determinism(recorder-owned wall stamp)
        with self._lock:
            ev["seq"] = self.seq
            self.seq += 1
            if len(self._ring) < self.capacity:
                self._ring.append(ev)
            else:
                self._ring[self._head] = ev
                self._head = (self._head + 1) % self.capacity

    def snapshot(self) -> list[dict]:
        """Events oldest-first (a copy; safe from any thread)."""
        with self._lock:
            return self._ring[self._head :] + self._ring[: self._head]

    def clear(self) -> None:
        """Drop all events and restart ``seq`` at 0 — a warmed-up test
        harness clears the ring so dumps compare across runs whose
        warmup event counts raced differently."""
        with self._lock:
            self._ring = []
            self._head = 0
            self.seq = 0

    # ---------------------------------------------------------------- dump
    def dump(
        self, path: str, reason: str, snapshot: dict | None = None
    ) -> str:
        """Append one dump block (header, events, snapshot) to ``path``.
        Never raises into the caller — a failing dump must not worsen
        whatever triggered it."""
        events = self.snapshot()
        try:
            dirname = os.path.dirname(os.path.abspath(path))
            os.makedirs(dirname, exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                f.write(
                    json.dumps(
                        {
                            "type": "flight_header",
                            "reason": reason,
                            "t": time.time(),  # dynlint: determinism(recorder-owned wall stamp)
                            "pid": os.getpid(),
                            "events": len(events),
                        }
                    )
                    + "\n"
                )
                for ev in events:
                    f.write(
                        json.dumps({"type": "flight_event", **ev}) + "\n"
                    )
                if snapshot is not None:
                    f.write(
                        json.dumps(
                            {
                                "type": "flight_snapshot",
                                "t": time.time(),  # dynlint: determinism(recorder-owned wall stamp)
                                **snapshot,
                            }
                        )
                        + "\n"
                    )
        except Exception:  # noqa: BLE001 - diagnostics must not cascade
            logger.exception("flight dump to %s failed", path)
        else:
            logger.warning(
                "flight recorder dumped %d events to %s (reason=%s)",
                len(events), path, reason,
            )
        return path


class Watchdog:
    """No-progress detector over an opaque progress counter.

    Fires ``dump_fn(reason)`` once per stall episode when ``has_work()``
    has been true and ``progress()`` unchanged for ``stall_s`` seconds;
    re-arms as soon as progress moves again. Progress is whatever
    monotonically-increasing integer the owner bumps on real forward
    motion (the engine bumps per loop iteration that dispatched,
    consumed, or admitted), so a loop stuck compiling, spinning on a dry
    pool with nothing to preempt, or deadlocked all look the same:
    frozen counter, queued work.
    """

    def __init__(
        self,
        stall_s: float,
        progress,  # () -> int
        has_work,  # () -> bool
        dump_fn,  # (reason: str) -> None
        poll_s: float | None = None,
    ):
        self.stall_s = stall_s
        self._progress = progress
        self._has_work = has_work
        self._dump = dump_fn
        self._poll_s = poll_s if poll_s is not None else max(stall_s / 4, 0.05)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.dumps = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="engine-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _run(self) -> None:
        last = self._progress()
        since = time.monotonic()
        fired = False
        while not self._stop.wait(self._poll_s):
            try:
                cur = self._progress()
                busy = self._has_work()
            except Exception:  # owner mid-teardown; try again next poll
                continue
            now = time.monotonic()
            if cur != last or not busy:
                last = cur
                since = now
                fired = False
                continue
            if not fired and now - since >= self.stall_s:
                fired = True  # once per episode
                self.dumps += 1
                try:
                    self._dump("watchdog")
                except Exception:  # noqa: BLE001
                    logger.exception("watchdog dump failed")


# ------------------------------------------------------- process registry
# Live engines register their dump callables so SIGUSR1 (and operators
# embedding several engines in one process) can dump every ring at once.
_dumpers: dict[int, object] = {}
_dumpers_lock = threading.Lock()


def register_dumper(dump_fn) -> int:
    """Register a ``(reason) -> None`` dump callable; returns a handle
    for :func:`unregister_dumper`."""
    with _dumpers_lock:
        handle = id(dump_fn)
        _dumpers[handle] = dump_fn
        return handle


def unregister_dumper(handle: int) -> None:
    with _dumpers_lock:
        _dumpers.pop(handle, None)


def dump_all(reason: str) -> int:
    """Dump every registered recorder; returns how many dumped."""
    with _dumpers_lock:
        fns = list(_dumpers.values())
    for fn in fns:
        try:
            fn(reason)
        except Exception:  # noqa: BLE001
            logger.exception("flight dump_all(%s) failed for one engine", reason)
    return len(fns)


def install_sigusr1() -> bool:
    """Chain a SIGUSR1 handler that dumps all registered recorders
    (keeps any existing handler). Main-thread only; returns False where
    signals aren't available."""
    import signal

    try:
        prev = signal.getsignal(signal.SIGUSR1)

        def handler(signum, frame):
            dump_all("sigusr1")
            if callable(prev):
                prev(signum, frame)

        signal.signal(signal.SIGUSR1, handler)
        return True
    except (ValueError, OSError, AttributeError):
        return False


# ------------------------------------------------------------- load/render
def load_dumps(path: str) -> list[dict]:
    """Parse a dump file into blocks:
    ``{"header": ..., "events": [...], "snapshot": ...|None}`` per dump
    (a file accumulates one block per dump). Corrupt lines (torn write
    at crash) are skipped."""
    blocks: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                logger.warning("skipping corrupt flight line")
                continue
            t = d.get("type")
            if t == "flight_header":
                blocks.append({"header": d, "events": [], "snapshot": None})
            elif blocks and t == "flight_event":
                blocks[-1]["events"].append(d)
            elif blocks and t == "flight_snapshot":
                blocks[-1]["snapshot"] = d
    return blocks


def _event_label(ev: dict) -> str:
    skip = {"type", "seq", "t", "kind", "slot", "req"}
    details = " ".join(
        f"{k}={ev[k]}" for k in sorted(ev) if k not in skip
    )
    return f"{ev['kind']}({details})" if details else ev["kind"]


def render_flight(block: dict) -> str:
    """Per-slot timeline of one dump block, ``llmctl trace`` style:
    batch-level events (dispatch/consume/chain breaks/leases) on an
    ``engine`` lane, per-request events on the slot they were bound to,
    and the snapshot's slot table — stalled slots flagged — at the
    bottom."""
    header = block.get("header") or {}
    events = block.get("events") or []
    snapshot = block.get("snapshot")
    if not events and snapshot is None:
        return "empty flight dump"
    t0 = min((ev["t"] for ev in events), default=header.get("t", 0.0))
    span = max((ev["t"] for ev in events), default=t0) - t0
    lines = [
        f"flight dump — reason={header.get('reason', '?')}, "
        f"{len(events)} events, {span * 1e3:.1f}ms span"
    ]
    # req -> slot from admit events (finish/preempt events carry slot
    # too; first sighting wins so a reused slot keeps per-request lanes
    # distinct enough to read).
    req_slot: dict[str, object] = {}
    for ev in events:
        if (
            "req" in ev
            and ev.get("slot") is not None
            and ev["req"] not in req_slot
        ):
            req_slot[ev["req"]] = ev["slot"]
    lanes: dict[object, list[dict]] = {}
    for ev in events:
        # An explicit slot=None (e.g. a finish for work never bound to
        # a slot) falls back to the request's admitted lane, not a
        # bogus "slot None" lane.
        slot = ev.get("slot")
        if slot is None:
            slot = req_slot.get(ev.get("req"), "engine")
        lanes.setdefault(slot, []).append(ev)

    def lane_key(k):
        return (1, k) if isinstance(k, int) else (0, str(k))

    for slot in sorted(lanes, key=lane_key):
        evs = lanes[slot]
        name = "engine" if slot == "engine" else f"slot {slot}"
        reqs = sorted({ev["req"] for ev in evs if "req" in ev})
        head = f"{name:<8}" + (f" [{', '.join(reqs)}]" if reqs else "")
        lines.append(head)
        for ev in evs:
            lines.append(
                f"  {ev['t'] - t0:9.3f}s  {_event_label(ev)}"
            )
    if snapshot is not None:
        lines.append("snapshot:")
        for k in sorted(snapshot):
            if k in ("type", "t", "slots"):
                continue
            lines.append(f"  {k}={snapshot[k]}")
        for s in snapshot.get("slots") or []:
            flag = "  STALLED" if s.get("stalled") else ""
            lines.append(
                f"  slot {s.get('slot')}  req={s.get('req')} "
                f"state={s.get('state')} generated={s.get('generated')} "
                f"pages={s.get('pages')}{flag}"
            )
    return "\n".join(lines)
