"""Speculative decoding: deterministic draft/verify (docs/speculative.md).

The load-bearing guarantee, proven on the CPU mesh: with speculation on,
every output stream is **token-identical** to the non-speculative run —
greedy, seeded, and penalized — across batch occupancies, draft lengths,
mid-window EOS, and preempt→resume under KV pressure. Plus units for the
n-gram prompt-lookup drafter, the adaptive controller, the
multi-position counter-keyed sampler, page rewind accounting, and the
acceptance telemetry. Compile-heavy identity matrices are ``slow``
(excluded from the time-boxed tier-1 lane, still in make test/nightly).
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput, SamplingOptions

from .test_engine import greedy_oracle

pytestmark = pytest.mark.spec

PS = 8
REPEAT_PROMPT = [5, 9, 17, 3] * 5  # gives the n-gram lookup something to hit


def make_engine(spec="ngram", **kw) -> TPUEngine:
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=kw.pop("max_decode_slots", 4),
        page_size=PS,
        num_pages=kw.pop("num_pages", 64),
        max_model_len=kw.pop("max_model_len", 128),
        eos_token_ids=kw.pop("eos_token_ids", []),
        # Default (bfloat16) KV: greedy_oracle runs the same dtype, so
        # engine-vs-oracle comparisons are exact.
        spec_mode=spec,
        **kw,
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def run(eng, prompt, max_tokens, stop_ids=(), **sampling):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = not stop_ids
    b.stop_conditions.stop_token_ids = list(stop_ids)
    if sampling:
        b.sampling_options = SamplingOptions(**sampling)
    stream = await eng.generate(b.to_dict())
    tokens, final = [], None
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
        if item.get("finish_reason"):
            final = item
    return tokens, final


@pytest.fixture(scope="module")
def plain_engine():
    eng = make_engine(spec="off")
    eng.start()
    yield eng
    eng.stop()


@pytest.fixture(scope="module")
def spec_engine():
    eng = make_engine(spec="ngram")
    eng.start()
    yield eng
    eng.stop()


# ------------------------------------------------------------ drafter units
def test_ngram_drafter_proposes_continuation_of_most_recent_match():
    from dynamo_exp_tpu.spec import NgramDrafter

    d = NgramDrafter(ngram_max=3, ngram_min=1)
    # trailing [1,2,3] occurred earlier, followed by [4,5]
    assert d.propose([1, 2, 3, 4, 5, 9, 1, 2, 3], 2) == [4, 5]
    # truncates to max_len
    assert d.propose([1, 2, 3, 4, 5, 9, 1, 2, 3], 1) == [4]
    # two occurrences: the MOST RECENT match's continuation wins
    toks = [1, 2, 7, 7, 1, 2, 8, 8, 1, 2]
    assert d.propose(toks, 2) == [8, 8]
    # no repeated n-gram (and no repeated unigram): no proposal
    assert d.propose([1, 2, 3, 4, 5], 4) == []
    # unigram fallback when wider n-grams miss
    assert d.propose([9, 1, 5, 2, 5], 1) == [2]


def test_drafter_registry_and_static():
    from dynamo_exp_tpu.spec import (
        StaticDrafter,
        build_drafter,
        registered_drafters,
    )

    assert "ngram" in registered_drafters()
    with pytest.raises(ValueError, match="unknown drafter"):
        build_drafter("no-such", None)
    s = StaticDrafter([7, 8, 9])
    assert s.propose([1, 2], 2) == [7, 8]


# --------------------------------------------------------- controller units
class _FakeSeq:
    def __init__(self, tokens, request_id="r1"):
        self.tokens = list(tokens)
        self.request_id = request_id


def _manager(**over):
    from dynamo_exp_tpu.spec import SpecManager

    cfg = EngineConfig(model=TINY, spec_mode="ngram", **over)
    return SpecManager(cfg)


def test_controller_grows_and_shrinks_draft_length():
    m = _manager(spec_draft_len=2, spec_min_draft=1, spec_max_draft=8)
    seq = _FakeSeq([1, 2, 1, 2])
    for _ in range(4):  # sustained full acceptance: length doubles to max
        m.record(seq, proposed=m.draft_len(seq), accepted=m.draft_len(seq))
    assert m.draft_len(seq) == 8
    for _ in range(6):  # sustained rejection: collapses to the floor
        m.record(seq, proposed=m.draft_len(seq), accepted=0)
    assert m.draft_len(seq) == 1


def test_controller_miss_backoff_reprobes_after_growth():
    m = _manager(spec_miss_limit=2, spec_retry_tokens=4)
    seq = _FakeSeq([1, 2, 3, 4, 5])  # nothing for the lookup to match
    assert m.wants_draft(seq)
    assert m.propose(seq) == []
    assert m.wants_draft(seq)  # one miss: still probing
    assert m.propose(seq) == []
    assert not m.wants_draft(seq)  # hit the miss limit: backed off
    seq.tokens += [6, 7, 8, 9]  # context grew past the retry point
    assert m.wants_draft(seq)
    # ...and the new context actually repeats now -> proposal resumes
    seq.tokens = [1, 2, 9, 9, 1, 2]
    assert m.propose(seq) != []


def test_controller_retain_drops_finished_rows():
    m = _manager()
    m.propose(_FakeSeq([1, 2], "a"))
    m.propose(_FakeSeq([1, 2], "b"))
    assert len(m) == 2
    m.retain({"b"})
    assert len(m) == 1


def test_adaptation_never_changes_tokens_only_dispatch_shape():
    """The controller is a perf knob, not a correctness one: whatever
    draft length it picks, the verify pass emits the target model's own
    tokens — proven end-to-end by every identity test in this file
    running with adaptation ON (the engine default)."""
    cfg = EngineConfig(model=TINY, spec_mode="ngram")
    assert cfg.spec_adaptive


# ------------------------------------------------------------- config units
def test_dyn_spec_env_toggle(monkeypatch):
    monkeypatch.setenv("DYN_SPEC", "ngram")
    assert EngineConfig(model=TINY).spec_mode == "ngram"
    monkeypatch.setenv("DYN_SPEC", "1")
    assert EngineConfig(model=TINY).spec_mode == "ngram"
    # Falsy spellings leave speculation off (not parsed as drafter names).
    for falsy in ("0", "false", "off", "no"):
        monkeypatch.setenv("DYN_SPEC", falsy)
        assert EngineConfig(model=TINY).spec_mode == "off", falsy
    monkeypatch.delenv("DYN_SPEC")
    assert EngineConfig(model=TINY).spec_mode == "off"


def test_spec_draft_bounds_validated():
    # Draft spans ride the mixed ragged token bucket (floor 16) — no
    # dedicated draft bucket family anymore (docs/engine_perf.md).
    cfg = EngineConfig(model=TINY, spec_max_draft=8)
    assert cfg.ragged_tokens_bucket_for(cfg.spec_max_draft + 1, mixed=True) == 16
    with pytest.raises(ValueError, match="spec draft bounds"):
        EngineConfig(model=TINY, spec_min_draft=4, spec_max_draft=2)


# ----------------------------------------------------------- sampling units
def test_multi_position_sampling_matches_per_position_draws():
    from dynamo_exp_tpu.ops.sampling import (
        sample_tokens_seeded,
        sample_tokens_seeded_multi,
    )

    rs = np.random.RandomState(0)
    B, T, V = 3, 4, 32
    logits = jnp.asarray(rs.randn(B, T, V).astype(np.float32))
    seeds = jnp.asarray([11, 22, 33], jnp.int32)
    positions = jnp.asarray(rs.randint(0, 100, size=(B, T)), jnp.int32)
    temp = jnp.asarray([0.0, 0.8, 1.2], jnp.float32)  # row 0 greedy
    top_k = jnp.asarray([0, 5, 0], jnp.int32)
    top_p = jnp.asarray([1.0, 1.0, 0.9], jnp.float32)
    multi = np.asarray(
        sample_tokens_seeded_multi(logits, seeds, positions, temp, top_k, top_p)
    )
    for t in range(T):
        single = np.asarray(
            sample_tokens_seeded(
                logits[:, t], seeds, positions[:, t], temp, top_k, top_p
            )
        )
        assert (multi[:, t] == single).all()


def test_spec_accept_length_rule():
    from dynamo_exp_tpu.ops.sampling import spec_accept_length

    targets = jnp.asarray([[4, 5, 6, 7], [4, 9, 6, 7], [1, 2, 3, 4]])
    drafts = jnp.asarray([[4, 5, 6], [4, 5, 6], [9, 2, 3]])
    n_drafts = jnp.asarray([3, 3, 2])
    # row 0: all 3 accepted + bonus; row 1: mismatch at i=1 -> 2 emitted;
    # row 2: first draft wrong -> correction only.
    assert np.asarray(
        spec_accept_length(targets, drafts, n_drafts)
    ).tolist() == [4, 2, 1]


def test_spec_verify_tokens_counts_only_emitted_positions():
    """Penalty-state rewind: counts gained by rejected positions must
    not survive the scan — only the emitted prefix is counted."""
    from dynamo_exp_tpu.ops.sampling import spec_verify_tokens

    B, T, V = 1, 3, 8
    # Greedy row (temp 0): argmax targets are [3, 3, 3].
    logits = np.full((B, T, V), -5.0, np.float32)
    logits[:, :, 3] = 5.0
    drafts = jnp.asarray([[3, 0]], jnp.int32)  # second draft wrong
    targets, n_emit, counts = spec_verify_tokens(
        jnp.asarray(logits),
        drafts,
        jnp.asarray([2], jnp.int32),
        jnp.asarray([7], jnp.int32),
        jnp.asarray([[10, 11, 12]], jnp.int32),
        jnp.asarray([0.0], jnp.float32),
        jnp.asarray([0], jnp.int32),
        jnp.asarray([1.0], jnp.float32),
        jnp.zeros((B, V), jnp.int32),
        jnp.asarray([0.0], jnp.float32),
        jnp.asarray([0.0], jnp.float32),
        jnp.asarray([1.0], jnp.float32),
    )
    assert np.asarray(targets)[0].tolist() == [3, 3, 3]
    assert int(n_emit[0]) == 2  # draft 0 accepted, draft 1 rejected
    # token 3 counted exactly twice: the accepted draft + the correction
    # — the rejected position's draw left no trace.
    assert int(np.asarray(counts)[0, 3]) == 2
    assert int(np.asarray(counts)[0].sum()) == 2


# -------------------------------------------------------- engine: identity
async def test_greedy_identity_and_speculation_engaged(spec_engine):
    """Spec-on greedy output equals the step-by-step oracle, and the
    repetitive prompt provably engaged speculation (drafts accepted,
    > 1 token per verify dispatch on average)."""
    accepted0 = spec_engine.spec_accepted_tokens
    tokens, final = await run(spec_engine, REPEAT_PROMPT, 16)
    assert tokens == greedy_oracle(REPEAT_PROMPT, 16)
    assert final["finish_reason"] == "length"
    assert final["completion_tokens"] == 16
    assert spec_engine.spec_accepted_tokens > accepted0
    m = spec_engine.metrics()
    assert m["spec_dispatches"] >= 1
    # Per-ROW basis (what bench/sim consume): tokens per verify
    # participation, not per batched device dispatch.
    assert m["spec_row_dispatches"] >= m["spec_dispatches"]
    assert m["spec_emitted_tokens"] / m["spec_row_dispatches"] > 1.0


async def test_seeded_identity(plain_engine, spec_engine):
    so = dict(temperature=0.9, top_p=0.9, seed=777)
    want, _ = await run(plain_engine, REPEAT_PROMPT, 16, **so)
    got, _ = await run(spec_engine, REPEAT_PROMPT, 16, **so)
    assert got == want


async def test_penalized_identity(plain_engine, spec_engine):
    so = dict(
        temperature=0.8,
        seed=424242,
        frequency_penalty=0.4,
        presence_penalty=0.2,
        repetition_penalty=1.15,
    )
    want, _ = await run(plain_engine, REPEAT_PROMPT, 20, **so)
    got, _ = await run(spec_engine, REPEAT_PROMPT, 20, **so)
    assert got == want


async def test_mid_window_eos_identity(plain_engine, spec_engine):
    """A stop token discovered inside a verify pass's emitted prefix
    must end both streams at the same token with the same reason."""
    free, _ = await run(plain_engine, REPEAT_PROMPT, 16)
    stop = free[4]  # force a stop partway through generation
    want, wfinal = await run(plain_engine, REPEAT_PROMPT, 16, stop_ids=[stop])
    got, gfinal = await run(spec_engine, REPEAT_PROMPT, 16, stop_ids=[stop])
    assert got == want
    assert want[-1] == stop and len(want) < 16
    assert wfinal["finish_reason"] == gfinal["finish_reason"] == "eos"


async def test_mixed_batch_identity(plain_engine, spec_engine):
    """Greedy and sampled rows sharing the engine (split verify
    partitions + plain windows) each stay identical to their solo
    non-speculative runs."""
    g_prompt = REPEAT_PROMPT
    s_prompt = [7, 3, 19, 7, 3, 19, 7, 3, 19, 28]
    so = dict(temperature=0.9, top_p=0.9, seed=123)
    want_g, _ = await run(plain_engine, g_prompt, 12)
    want_s, _ = await run(plain_engine, s_prompt, 12, **so)
    got_g, got_s = await asyncio.gather(
        run(spec_engine, g_prompt, 12),
        run(spec_engine, s_prompt, 12, **so),
    )
    assert got_g[0] == want_g
    assert got_s[0] == want_s


async def test_no_page_leak_and_rewind_accounting():
    """Verify-pass page provisioning must rewind: after every stream
    finishes, the pool is whole (free == reclaimable + untouched), even
    though rejected drafts had pages provisioned past the accepted
    prefix."""
    eng = make_engine(spec="ngram", num_pages=32)
    eng.start()
    try:
        tokens, _ = await run(eng, REPEAT_PROMPT, 16)
        assert tokens == greedy_oracle(REPEAT_PROMPT, 16)
        for _ in range(200):
            if not eng.sched.has_work():
                break
            await asyncio.sleep(0.01)
        assert eng.kv.free_pages == eng.kv.num_pages
        assert eng.spec_draft_tokens >= eng.spec_accepted_tokens
    finally:
        eng.stop()


async def test_spec_telemetry_counters_exposed(spec_engine):
    """Acceptance counters ride /metrics and the metrics() mirrors."""
    from dynamo_exp_tpu.telemetry import get_telemetry

    await run(spec_engine, REPEAT_PROMPT, 8)
    m = spec_engine.metrics()
    for key in (
        "spec_dispatches",
        "spec_row_dispatches",
        "spec_draft_tokens",
        "spec_accepted_tokens",
        "spec_emitted_tokens",
        "compiled_ragged_variants",
    ):
        assert key in m
    # Verify passes ride the ONE ragged variant cache (no dedicated
    # spec-fn family anymore, docs/engine_perf.md).
    assert m["compiled_ragged_variants"] == len(spec_engine._ragged_fns) > 0
    rendered = get_telemetry().render().decode()
    assert "dynamo_spec_draft_tokens_total" in rendered
    assert "dynamo_spec_accepted_tokens_total" in rendered
    assert "dynamo_spec_tokens_per_dispatch" in rendered


# --------------------------------------------- slow: full identity matrices
@pytest.mark.slow  # compile-heavy: one engine per draft length
@pytest.mark.parametrize("draft_len", [2, 4, 8])
async def test_identity_matrix_across_draft_lengths(plain_engine, draft_len):
    """Greedy AND seeded AND penalized, 3 seeds each, at the pinned
    draft length — token-identical to the non-speculative engine."""
    eng = make_engine(
        spec="ngram",
        spec_draft_len=draft_len,
        spec_max_draft=draft_len,
        spec_adaptive=False,
    )
    eng.start()
    try:
        want, _ = await run(plain_engine, REPEAT_PROMPT, 16)
        got, _ = await run(eng, REPEAT_PROMPT, 16)
        assert got == want, f"greedy diverged at draft_len={draft_len}"
        for seed in (7, 21, 1337):
            so = dict(temperature=0.9, top_p=0.9, seed=seed)
            want, _ = await run(plain_engine, REPEAT_PROMPT, 14, **so)
            got, _ = await run(eng, REPEAT_PROMPT, 14, **so)
            assert got == want, f"seeded diverged seed={seed} d={draft_len}"
            pso = dict(
                temperature=0.8,
                seed=seed,
                frequency_penalty=0.3,
                repetition_penalty=1.1,
            )
            want, _ = await run(plain_engine, REPEAT_PROMPT, 14, **pso)
            got, _ = await run(eng, REPEAT_PROMPT, 14, **pso)
            assert got == want, f"penalized diverged seed={seed} d={draft_len}"
    finally:
        eng.stop()


@pytest.mark.slow  # wide row buckets: extra compiled variants
async def test_identity_at_mixed_occupancies(plain_engine):
    """Occupancy 1 vs 3-of-4 slots: per-row streams never see the batch
    around them (the compaction + counter-keyed sampling invariant,
    now through verify dispatches too)."""
    eng = make_engine(spec="ngram")
    eng.start()
    try:
        prompts = [
            REPEAT_PROMPT,
            [11, 4, 11, 4, 11, 4, 9],
            [3, 19, 28, 3, 19, 28, 3, 19],
        ]
        sos = [
            {},
            dict(temperature=0.9, top_p=0.9, seed=55),
            dict(temperature=0.7, seed=66, frequency_penalty=0.2),
        ]
        solos = [
            (await run(plain_engine, p, 12, **so))[0]
            for p, so in zip(prompts, sos)
        ]
        # occupancy 1
        got, _ = await run(eng, prompts[0], 12, **sos[0])
        assert got == solos[0]
        # occupancy 3 (mixed greedy/seeded/penalized rows)
        results = await asyncio.gather(
            *[run(eng, p, 12, **so) for p, so in zip(prompts, sos)]
        )
        for i, (got, _) in enumerate(results):
            assert got == solos[i], f"row {i} diverged at occupancy 3"
    finally:
        eng.stop()


@pytest.mark.slow  # pressure engine + oracle replays: compile-heavy
async def test_preempt_resume_identity_with_spec_on():
    """KV-pressure preemption under speculation: the preempted stream
    resumes as a deterministic continuation and stays token-identical
    to the un-pressured run (the same oracle trick as test_overload:
    one request alone never stalls on this pool, and counter-based
    sampling makes tokens pool-independent)."""
    eng = make_engine(
        spec="ngram",
        num_pages=8,
        preempt_stall_grace_s=0.05,
    )
    eng.start()
    try:
        prompts = [REPEAT_PROMPT[:8], [9, 2, 9, 2, 9, 2, 9, 5]]
        sos = [{}, dict(temperature=0.9, seed=99)]
        n = 40
        solos = []
        for p, so in zip(prompts, sos):  # sequential: no pressure
            toks, _ = await run(eng, p, n, **so)
            assert len(toks) == n
            solos.append(toks)
        preempted0 = eng.preempted
        results = await asyncio.gather(
            *[run(eng, p, n, **so) for p, so in zip(prompts, sos)]
        )
        assert eng.preempted > preempted0, "pool never pressured?"
        for i, (toks, final) in enumerate(results):
            assert toks == solos[i], f"stream {i} diverged across preemption"
            assert final["finish_reason"] == "length"
    finally:
        eng.stop()
