"""SDK tests: decorators, graph discovery, config merge, allocator, and
the hello_world 3-process e2e through the real supervisor.

Reference capability anchors: ``deploy/dynamo/sdk`` tests
(``test_config.py``, ``test_link.py``, ``test_e2e.py`` with the toy
pipeline fixture) and ``examples/hello_world``.
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from dynamo_exp_tpu.sdk import ServiceConfig, depends, endpoint, get_spec, service
from dynamo_exp_tpu.sdk.allocator import AllocationError, TPUAllocator
from dynamo_exp_tpu.sdk.service import discover_graph

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- decorators
def test_service_spec_and_graph_discovery():
    from examples.hello_world.hello_world import Backend, Frontend, Middle

    spec = get_spec(Frontend)
    assert spec.namespace == "hello"
    assert "generate" in spec.endpoints
    names = [s.name for s in discover_graph(Frontend)]
    # Dependencies first, root last.
    assert names == ["Backend", "Middle", "Frontend"]
    assert get_spec(Middle).cls is Middle
    assert get_spec(Backend).workers == 1


def test_endpoint_decorator_forms():
    @service()
    class S:
        @endpoint
        async def bare(self, request):
            yield {}

        @endpoint("named")
        async def other(self, request):
            yield {}

    spec = get_spec(S)
    assert set(spec.endpoints) == {"bare", "named"}


def test_depends_unresolved_raises():
    from examples.hello_world.hello_world import Middle

    with pytest.raises(RuntimeError, match="not resolved"):
        _ = Middle().backend


# -------------------------------------------------------------------- config
def test_service_config_yaml_env_merge(tmp_path, monkeypatch):
    cfg = tmp_path / "c.yaml"
    cfg.write_text("Frontend:\n  greeting: hi\n  depth: 1\nMiddle:\n  x: 2\n")
    monkeypatch.setenv(
        "DYN_SERVICE_CONFIG", json.dumps({"Frontend": {"depth": 9}})
    )
    sc = ServiceConfig.load(str(cfg))
    assert sc.get("Frontend") == {"greeting": "hi", "depth": 9}  # env wins
    assert sc.get("Middle") == {"x": 2}
    assert sc.get("Nope") == {}

    class Obj:
        pass

    o = Obj()
    sc.apply_to(o, "Frontend")
    assert o.greeting == "hi" and o.depth == 9


# ----------------------------------------------------------------- allocator
def test_tpu_allocator_assigns_disjoint_chips():
    alloc = TPUAllocator(total_chips=4)
    a = alloc.assign("decode", 2)
    b = alloc.assign("prefill", 2)
    assert a["TPU_VISIBLE_CHIPS"] == "0,1"
    assert b["TPU_VISIBLE_CHIPS"] == "2,3"
    with pytest.raises(AllocationError):
        alloc.assign("extra", 1)
    # Host-side services stay off the TPU.
    assert alloc.assign("frontend", 0) == {"JAX_PLATFORMS": "cpu"}


# ----------------------------------------------------------------------- e2e
async def test_hello_world_graph_end_to_end():
    """Real supervisor, three service processes, request through the
    full Frontend->Middle->Backend chain, config override applied."""
    from dynamo_exp_tpu.runtime.component import DistributedRuntime
    from dynamo_exp_tpu.runtime.config import RuntimeConfig
    from dynamo_exp_tpu.runtime.transports.coordinator import CoordinatorServer

    server = CoordinatorServer()
    await server.start()
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        DYN_SERVICE_CONFIG=json.dumps({"Frontend": {"greeting": "bonjour"}}),
    )
    sup = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "dynamo_exp_tpu.sdk.serve",
        "examples.hello_world.hello_world:Frontend",
        "--coordinator", server.address,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    drt = DistributedRuntime(
        config=RuntimeConfig(coordinator_endpoint=server.address)
    )
    try:
        ep = drt.namespace("hello").component("Frontend").endpoint("generate")
        client = await ep.client()
        for _ in range(200):
            if client.instances or sup.returncode is not None:
                break
            await asyncio.sleep(0.1)
        if not client.instances:
            out = b""
            if sup.returncode is not None:
                out = await sup.stdout.read()
            raise AssertionError(
                f"Frontend never came up (sup rc={sup.returncode}):\n"
                + out.decode()
            )

        from dynamo_exp_tpu.runtime.push_router import PushRouter

        router = PushRouter(client)
        stream = await router.generate({"text": "world"})
        tokens = [item["token"] async for item in stream]
        assert tokens == ["bonjour", "world-mid-back"]
    finally:
        sup.terminate()
        try:
            await asyncio.wait_for(sup.wait(), 30)
        except asyncio.TimeoutError:
            sup.kill()
        await drt.close()
        await server.close()
