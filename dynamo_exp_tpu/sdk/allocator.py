"""TPU chip allocation for multi-service hosts.

Reference parity: ``deploy/dynamo/sdk/cli/allocator.py:53-120``
(``ResourceAllocator.assign_gpus`` → ``CUDA_VISIBLE_DEVICES`` per
watcher). TPU equivalent: disjoint chip sets per service process via
``TPU_VISIBLE_CHIPS`` (libtpu) — also exported as
``TPU_VISIBLE_DEVICES`` for older runtimes. A service asks with
``resources={"tpu": n}``; services with no tpu request get no chips and
the TPU runtime is told to stay off (``JAX_PLATFORMS=cpu``), so
frontends/routers never grab the accelerator.
"""

from __future__ import annotations

import os


class AllocationError(RuntimeError):
    pass


class TPUAllocator:
    def __init__(self, total_chips: int | None = None):
        if total_chips is None:
            total_chips = int(os.environ.get("DYN_TPU_CHIPS", "4"))
        self.total_chips = total_chips
        self._next = 0

    def assign(self, service_name: str, chips: int) -> dict[str, str]:
        """Env vars for one worker process of ``service_name``."""
        if chips <= 0:
            # Host-side service: keep JAX off the TPU entirely.
            return {"JAX_PLATFORMS": "cpu"}
        if self._next + chips > self.total_chips:
            raise AllocationError(
                f"{service_name} wants {chips} TPU chips but only "
                f"{self.total_chips - self._next} of {self.total_chips} remain"
            )
        ids = ",".join(str(i) for i in range(self._next, self._next + chips))
        self._next += chips
        return {"TPU_VISIBLE_CHIPS": ids, "TPU_VISIBLE_DEVICES": ids}
