"""The declarative knob space (docs/tuning.md).

One registry maps every *tunable* ``EngineConfig`` / ``PlannerConfig``
/ ``SloTargets`` / ``SimConfig`` field to its type, bounds, candidate
grid, and sim-vs-live applicability. Everything downstream derives
from it:

- the search (:mod:`.search`) walks the sim-applicable knobs' grids;
- ``bench.py`` stamps every JSON line with the engine's resolved knob
  dict and its :func:`config_hash`, so ``llmctl bench compare`` never
  silently compares differently-knobbed runs;
- the docs knob table renders from :func:`render_knob_table` and a
  doc-sync guard keeps docs/tuning.md listing every knob;
- a registry-walk guard test (tests/test_tune.py) asserts the registry
  and the config dataclasses cannot drift: every bool/int/float field
  of an owning config is either registered here or explicitly
  allowlisted in :data:`NON_TUNABLE`, and every registered knob's
  default sits on its own grid.

The module is dynlint determinism-zoned: registry order, hashes, and
grids must be bit-identical across processes and hosts (the journal
and the artifact both embed :func:`space_digest`).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, fields


@dataclass(frozen=True)
class Knob:
    """One tunable field of an owning config dataclass.

    ``grid`` is the finite, ordered candidate set the search walks —
    it must contain the owning dataclass's default (the guard test
    asserts it). ``sim`` marks knobs the simulator can evaluate
    (directly, or through ``sim_field`` for engine knobs that map onto
    a SimConfig mirror); ``live`` marks knobs a live engine boot
    honors. A knob can be both."""

    name: str
    owner: str  # "engine" | "planner" | "slo" | "sim"
    kind: str  # "int" | "float" | "bool"
    grid: tuple
    sim: bool = True
    live: bool = True
    sim_field: str | None = None  # SimConfig mirror of an engine knob
    note: str = ""


KNOBS: tuple[Knob, ...] = (
    # ------------------------------------------------------------ engine
    Knob("max_decode_slots", "engine", "int", (4, 8, 16, 32),
         sim_field="slots_per_instance",
         note="decode batch envelope (B of the decode step)"),
    Knob("num_pages", "engine", "int", (128, 256, 512, 1024, 2048),
         sim_field="pages_per_instance",
         note="global KV page pool size"),
    Knob("page_size", "engine", "int", (8, 16, 32),
         sim_field="page_size",
         note="tokens per KV page (also the reuse-hash block)"),
    Knob("prefill_batch", "engine", "int", (4, 8, 16), sim=False,
         note="sequences sharing one prefill dispatch"),
    Knob("prefill_chunk", "engine", "int", (128, 256, 512, 1024),
         sim=False,
         note="prompt tokens fed per chunk (decode interleaves between)"),
    Knob("decode_window", "engine", "int", (4, 8, 16, 32), sim=False,
         note="decode steps per compiled dispatch (host syncs once)"),
    Knob("preempt_stall_grace_s", "engine", "float",
         (0.1, 0.25, 0.5, 1.0),
         sim_field="preempt_stall_grace_s",
         note="hard-stall grace before KV-pressure preemption"),
    Knob("max_preemptions_per_seq", "engine", "int", (0, 1, 2, 4),
         sim_field="max_preemptions_per_seq",
         note="victimization bound per sequence (live-lock guard)"),
    Knob("prefix_sharing", "engine", "bool", (False, True),
         sim_field="prefix_sharing",
         note="refcounted copy-on-write shared prefix pages"),
    Knob("kv_packing", "engine", "bool", (False, True),
         sim_field="kv_packing",
         note="footprint-packed admission vs first-fit"),
    Knob("packing_scan_limit", "engine", "int", (4, 8, 16, 32, 64),
         sim_field="packing_scan_limit",
         note="waiting-queue prefix scanned per packing pass"),
    Knob("packing_max_defers", "engine", "int", (16, 32, 64, 128),
         sim_field="packing_max_defers",
         note="bypasses before a deferred seq becomes a barrier"),
    Knob("host_cache_pages", "engine", "int", (0, 64, 256, 1024),
         sim_field="host_pages_per_instance",
         note="G2 host-RAM KV tier size (0 disables offload)"),
    Knob("kv_prefetch", "engine", "bool", (False, True), sim=False,
         note="G2->G1 prefetch of waiting prompts' host prefixes"),
    Knob("prefetch_depth", "engine", "int", (1, 2, 4, 8), sim=False,
         note="waiting sequences scanned per prefetch pass"),
    Knob("prefetch_reserve_pages", "engine", "int", (0, 2, 4, 8),
         sim=False,
         note="free-page headroom prefetch never consumes"),
    Knob("proactive_offload_grace_s", "engine", "float",
         (0.0, 0.1, 0.25), sim=False,
         note="stall grace before cold-tail swap-out (< preempt grace)"),
    Knob("ragged_q_tile", "engine", "int", (1, 4, 8, 16), sim=False,
         note="flat-stream row alignment of the Pallas ragged kernel"),
    # ----------------------------------------------------------- planner
    Knob("adjustment_interval", "planner", "float", (5.0, 10.0, 20.0),
         live=True, note="seconds between planner adjustment rounds"),
    Knob("prefill_queue_scale_up_threshold", "planner", "float",
         (3.0, 5.0, 8.0), note="reactive prefill scale-up trigger"),
    Knob("prefill_queue_scale_down_threshold", "planner", "float",
         (0.1, 0.2, 0.5), note="reactive prefill scale-down trigger"),
    Knob("decode_kv_scale_up_threshold", "planner", "float",
         (0.7, 0.8, 0.9), note="reactive decode KV scale-up trigger"),
    Knob("decode_kv_scale_down_threshold", "planner", "float",
         (0.3, 0.5, 0.6), note="reactive decode KV scale-down trigger"),
    Knob("waiting_request_kv_estimate", "planner", "float",
         (0.01, 0.02, 0.05),
         note="KV fraction one waiting request is assumed to claim"),
    # --------------------------------------------------------------- slo
    Knob("decode_kv_target", "slo", "float", (0.6, 0.75, 0.85),
         note="per-worker KV load the SLO planner sizes the fleet to"),
    Knob("prefill_queue_target", "slo", "float", (1.0, 2.0, 4.0),
         note="per-worker prefill queue depth target"),
    Knob("forecast_horizon", "slo", "float", (1.0, 2.0, 3.0),
         note="look-ahead windows along the observed trend"),
    Knob("scale_down_headroom", "slo", "float", (0.4, 0.6),
         note="pressure below this fraction sheds one worker"),
    Knob("max_scale_step", "slo", "int", (1, 2, 4),
         note="most workers added/removed in one round"),
    # ------------------------------------------------------ sim/edge only
    Knob("max_inflight", "sim", "int", (16, 32, 64, 128), live=False,
         note="edge admission bound (AdmissionController)"),
    Knob("queue_weight", "sim", "float", (0.5, 1.0, 2.0), live=False,
         note="routing: queue-depth weight in worker selection"),
)

KNOB_BY_NAME: dict[str, Knob] = {k.name: k for k in KNOBS}

# Registry-walk allowlist: bool/int/float fields of the owning configs
# that are deliberately NOT tunable. The guard test asserts
# registered + allowlisted covers every such field exactly — adding a
# config field without deciding its tunability breaks the build.
NON_TUNABLE: dict[str, frozenset] = {
    "engine": frozenset({
        # Parallelism/topology and workload contract, not perf knobs.
        "tp", "sp", "max_model_len", "default_max_tokens",
        # Correctness/debug toggles (A/B and equivalence runs only).
        "pallas_interpret", "chained_decode", "enable_kv_events",
        "profile_dispatches", "kv_ledger_check",
        # Static stop-set width: a compile-key shape, sized to the API
        # contract (requests with more stop ids fall back to host).
        "device_stop_width",
        # Observability plane (flight ring, watchdog, leases).
        "flight_events", "flight_capacity", "watchdog_stall_s",
        "kv_lease_ttl_s",
        # Durable G3 tier capacity: sized to the local SSD, a
        # provisioning decision like max_tpu_budget, not a perf knob.
        "kv_store_pages",
        # Speculation is tuned online by the adaptive controller
        # (spec/controller.py); static search would fight it.
        "spec_draft_len", "spec_min_draft", "spec_max_draft",
        "spec_adaptive", "spec_ngram", "spec_ngram_min",
        "spec_miss_limit", "spec_retry_tokens",
    }),
    "planner": frozenset({
        # Budget/topology constraints and loop mechanics.
        "metric_pulling_interval", "max_tpu_budget",
        "decode_engine_num_tpu", "prefill_engine_num_tpu",
        "min_endpoint", "no_operation",
    }),
    "slo": frozenset({
        # The SLO contract itself (targets are inputs, not knobs) and
        # measured hints.
        "ttft_p99_slo_s", "itl_p99_slo_s", "max_pressure",
        "provision_s",
    }),
    "sim": frozenset({
        # Engine mirrors (tuned through their engine knob), workload /
        # fleet / economics model parameters, and bookkeeping.
        "seed", "slots_per_instance", "pages_per_instance", "page_size",
        "preempt_stall_grace_s", "max_preemptions_per_seq",
        "admission_per_instance", "prefix_sharing", "kv_packing",
        "packing_scan_limit", "packing_max_defers",
        "host_pages_per_instance", "proactive_offload",
        "initial_instances", "spot_fraction", "reclaim_rate_per_min",
        "reclaim_grace_s", "reclaim_margin_s", "migration_bw_bps",
        "kv_bytes_per_page", "spot_cost_factor", "record_events",
        "max_events",
        # Durable-KV restart drill (docs/fault_tolerance.md): store
        # capacity / restore-cost model parameters, not perf knobs.
        "g3_pages_per_instance", "g3_restore_s_per_page",
    }),
}


def owner_classes() -> dict[str, type]:
    """The owning config dataclass per owner key (lazy: SimConfig pulls
    the whole policy import graph)."""
    from ..engine.config import EngineConfig
    from ..planner.planner import PlannerConfig
    from ..planner.policy import SloTargets
    from ..sim.cluster import SimConfig

    return {
        "engine": EngineConfig,
        "planner": PlannerConfig,
        "slo": SloTargets,
        "sim": SimConfig,
    }


def default_value(knob: Knob):
    """The owning dataclass's declared default for this knob."""
    cls = owner_classes()[knob.owner]
    for f in fields(cls):
        if f.name == knob.name:
            return f.default
    raise KeyError(f"{knob.owner} config has no field {knob.name!r}")


def defaults(owner: str | None = None) -> dict:
    """name -> dataclass default, for every knob (or one owner's)."""
    return {
        k.name: default_value(k)
        for k in KNOBS
        if owner is None or k.owner == owner
    }


def sim_knobs(planner: bool = False) -> tuple[Knob, ...]:
    """The knobs a simulator evaluation can observe: engine knobs with
    a SimConfig mirror plus sim-only edge knobs; planner/slo knobs only
    when the evaluation runs a planner."""
    out = []
    for k in KNOBS:
        if not k.sim:
            continue
        if k.owner in ("planner", "slo") and not planner:
            continue
        out.append(k)
    return tuple(out)


def live_knobs() -> tuple[Knob, ...]:
    return tuple(k for k in KNOBS if k.live)


def split_overrides(overrides: dict) -> dict[str, dict]:
    """Partition an overrides dict by owner (unknown names raise)."""
    out: dict[str, dict] = {"engine": {}, "planner": {}, "slo": {}, "sim": {}}
    for name in sorted(overrides):
        knob = KNOB_BY_NAME.get(name)
        if knob is None:
            raise KeyError(
                f"unknown knob {name!r}; registered: {sorted(KNOB_BY_NAME)}"
            )
        out[knob.owner][name] = overrides[name]
    return out


def sim_kwargs_from_overrides(overrides: dict) -> dict:
    """Map a knob-overrides dict onto SimConfig keyword arguments
    (engine knobs through their ``sim_field`` mirror; live-only knobs
    are dropped — the simulator cannot observe them)."""
    out: dict = {}
    for name in sorted(overrides):
        knob = KNOB_BY_NAME.get(name)
        if knob is None:
            raise KeyError(
                f"unknown knob {name!r}; registered: {sorted(KNOB_BY_NAME)}"
            )
        if not knob.sim:
            continue
        if knob.owner == "engine":
            if knob.sim_field:
                out[knob.sim_field] = overrides[name]
        elif knob.owner == "sim":
            out[name] = overrides[name]
    return out


def engine_kwargs_from_overrides(overrides: dict) -> dict:
    """The live-applicable engine-knob subset of an overrides dict,
    ready to splat into ``EngineConfig(...)``."""
    return {
        name: val
        for name, val in sorted(overrides.items())
        if (k := KNOB_BY_NAME.get(name)) is not None
        and k.owner == "engine"
        and k.live
    }


def resolved_engine_knobs(cfg) -> dict:
    """Every registered engine knob's resolved value on an
    ``EngineConfig`` instance — the dict ``bench.py`` stamps on every
    JSON line next to its :func:`config_hash`."""
    return {k.name: getattr(cfg, k.name) for k in KNOBS if k.owner == "engine"}


def config_hash(knobs: dict) -> str:
    """Stable short hash of a resolved knob dict: the pairing key
    ``llmctl bench compare`` uses so differently-knobbed runs never
    silently compare. Canonical JSON, so dict order cannot leak in."""
    blob = json.dumps(knobs, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def space_digest() -> str:
    """Identity of the registry itself (names, grids, applicability):
    embedded in trial journals and artifacts so a resumed or replayed
    run detects a space change instead of mixing incompatible trials."""
    blob = json.dumps(
        [
            {
                "name": k.name,
                "owner": k.owner,
                "kind": k.kind,
                "grid": list(k.grid),
                "sim": k.sim,
                "live": k.live,
                "sim_field": k.sim_field,
            }
            for k in KNOBS
        ],
        sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def render_knob_table() -> str:
    """The docs/tuning.md knob table (generated, guard-synced)."""
    lines = [
        "| knob | owner | type | grid | sim | live | what it does |",
        "|---|---|---|---|---|---|---|",
    ]
    for k in KNOBS:
        grid = ", ".join(str(v) for v in k.grid)
        lines.append(
            f"| `{k.name}` | {k.owner} | {k.kind} | {grid} "
            f"| {'yes' if k.sim else '-'} | {'yes' if k.live else '-'} "
            f"| {k.note} |"
        )
    return "\n".join(lines)
