"""HTTP service tests: real aiohttp server + client, streaming SSE,
aggregation, metrics, model registry."""

import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_exp_tpu.engines.echo import EchoEngineCore, EchoEngineFull
from dynamo_exp_tpu.http import HttpService, ModelManager, build_pipeline_engine
from dynamo_exp_tpu.model_card import ModelDeploymentCard


async def make_client(service: HttpService) -> TestClient:
    client = TestClient(TestServer(service.app))
    await client.start_server()
    return client


def chat_body(stream: bool, model: str = "echo") -> dict:
    return {
        "model": model,
        "messages": [{"role": "user", "content": "hello world"}],
        "stream": stream,
    }


@pytest.mark.asyncio
async def test_models_and_health():
    svc = HttpService()
    svc.manager.add_chat_model("m1", EchoEngineFull())
    client = await make_client(svc)
    r = await client.get("/v1/models")
    data = await r.json()
    assert [m["id"] for m in data["data"]] == ["m1"]
    r = await client.get("/health")
    assert (await r.json())["status"] == "healthy"
    await client.close()


@pytest.mark.asyncio
async def test_chat_unary_aggregates_stream():
    svc = HttpService()
    svc.manager.add_chat_model("echo", EchoEngineFull())
    client = await make_client(svc)
    r = await client.post("/v1/chat/completions", json=chat_body(stream=False))
    assert r.status == 200
    data = await r.json()
    assert data["choices"][0]["message"]["content"] == "hello world"
    assert data["object"] == "chat.completion"
    await client.close()


@pytest.mark.asyncio
async def test_chat_streaming_sse():
    svc = HttpService()
    svc.manager.add_chat_model("echo", EchoEngineFull(chunk_chars=3))
    client = await make_client(svc)
    r = await client.post("/v1/chat/completions", json=chat_body(stream=True))
    assert r.status == 200
    assert r.headers["Content-Type"].startswith("text/event-stream")
    raw = (await r.read()).decode()
    assert raw.strip().endswith("data: [DONE]")
    pieces = []
    for line in raw.split("\n"):
        if line.startswith("data: ") and line != "data: [DONE]":
            chunk = json.loads(line[6:])
            for choice in chunk["choices"]:
                if choice["delta"].get("content"):
                    pieces.append(choice["delta"]["content"])
    assert "".join(pieces) == "hello world"
    await client.close()


@pytest.mark.asyncio
async def test_unknown_model_404():
    svc = HttpService()
    client = await make_client(svc)
    r = await client.post("/v1/chat/completions", json=chat_body(stream=False))
    assert r.status == 404
    assert (await r.json())["error"]["type"] == "model_not_found"
    await client.close()


@pytest.mark.asyncio
async def test_invalid_body_400():
    svc = HttpService()
    client = await make_client(svc)
    r = await client.post("/v1/chat/completions", json={"model": "m"})
    assert r.status == 400
    await client.close()


@pytest.mark.asyncio
async def test_no_instances_maps_to_503_with_retry_after():
    """NoInstancesError (empty fleet) → 503 + Retry-After, unary path."""
    from dynamo_exp_tpu.runtime import Client, PushRouter
    from dynamo_exp_tpu.runtime.transports.inproc import InProcRequestPlane

    svc = HttpService()
    # A real router over a static client with zero instances.
    router = PushRouter(Client.new_static(InProcRequestPlane(), []))
    svc.manager.add_chat_model("echo", router)
    client = await make_client(svc)
    r = await client.post("/v1/chat/completions", json=chat_body(stream=False))
    assert r.status == 503
    assert r.headers["Retry-After"] == "1"
    assert (await r.json())["error"]["type"] == "service_unavailable"
    await client.close()


@pytest.mark.asyncio
async def test_breaker_open_maps_to_503_with_retry_after():
    """NoHealthyInstancesError (instances exist, all breaker-open or
    draining) takes the same 503 path."""
    from dynamo_exp_tpu.runtime import NoHealthyInstancesError

    class AllUnhealthyEngine:
        async def generate(self, request, context=None):
            raise NoHealthyInstancesError("all 2 instances unhealthy")

    svc = HttpService()
    svc.manager.add_chat_model("echo", AllUnhealthyEngine())
    client = await make_client(svc)
    r = await client.post("/v1/chat/completions", json=chat_body(stream=False))
    assert r.status == 503
    assert r.headers["Retry-After"] == "1"
    assert "unhealthy" in (await r.json())["error"]["message"]
    await client.close()


@pytest.mark.asyncio
async def test_engine_error_mid_stream_emits_sse_error_frame():
    """EngineError once streaming has begun → in-band SSE error frame +
    clean stream termination (no [DONE], no broken connection)."""
    from dynamo_exp_tpu.runtime import (
        AsyncEngineContext,
        EngineError,
        ResponseStream,
    )

    class MidStreamFailEngine:
        async def generate(self, request, context=None):
            ctx = context or AsyncEngineContext()

            async def _gen():
                yield {
                    "id": "x",
                    "object": "chat.completion.chunk",
                    "created": 1,
                    "model": "echo",
                    "choices": [
                        {"index": 0, "delta": {"content": "partial"}}
                    ],
                }
                raise EngineError("worker died mid-stream")

            return ResponseStream(_gen(), ctx)

    svc = HttpService()
    svc.manager.add_chat_model("echo", MidStreamFailEngine())
    client = await make_client(svc)
    r = await client.post("/v1/chat/completions", json=chat_body(stream=True))
    assert r.status == 200  # headers were already sent when the error hit
    raw = (await r.read()).decode()  # reading to EOF: terminated cleanly
    events = [line for line in raw.split("\n") if line.startswith("event: ")]
    assert "event: error" in events
    assert "worker died mid-stream" in raw
    assert "data: [DONE]" not in raw  # an errored stream must not claim success
    assert "partial" in raw  # the pre-error output was delivered
    await client.close()


@pytest.mark.asyncio
async def test_request_timeout_arms_deadline_and_maps_to_504():
    """``timeout_s`` (body) arms the per-request deadline on the engine
    context; a deadline-exceeded request maps to 504."""
    from dynamo_exp_tpu.runtime import AsyncEngineContext, DeadlineExceededError

    seen: dict = {}

    import asyncio

    class DeadlineEngine:
        async def generate(self, request, context=None):
            ctx = context or AsyncEngineContext()
            seen["remaining"] = ctx.time_remaining()
            # Simulate work outlasting the budget, then hit the gate the
            # router/remote stages apply.
            while not ctx.deadline_expired:
                await asyncio.sleep(0.001)
            ctx.check_deadline("router")
            raise AssertionError("unreachable: deadline already expired")

    svc = HttpService()
    svc.manager.add_chat_model("echo", DeadlineEngine())
    client = await make_client(svc)
    body = {**chat_body(stream=False), "timeout_s": 0.005}
    r = await client.post("/v1/chat/completions", json=body)
    assert r.status == 504
    assert (await r.json())["error"]["type"] == "deadline_exceeded"
    assert seen["remaining"] is not None  # the context carried a deadline
    # The header variant arms it too.
    r = await client.post(
        "/v1/chat/completions",
        json=chat_body(stream=False),
        headers={"X-Request-Timeout-S": "0.005"},
    )
    assert r.status == 504
    # Invalid budget is a 400, not a silent no-deadline.
    r = await client.post(
        "/v1/chat/completions", json={**chat_body(stream=False), "timeout_s": -5}
    )
    assert r.status == 400
    await client.close()


@pytest.mark.asyncio
async def test_metrics_exposed_after_requests():
    svc = HttpService()
    svc.manager.add_chat_model("echo", EchoEngineFull())
    client = await make_client(svc)
    await client.post("/v1/chat/completions", json=chat_body(stream=False))
    r = await client.get("/metrics")
    text = await r.text()
    assert "llm_http_service_requests_total" in text
    assert 'model="echo"' in text
    await client.close()


@pytest.mark.asyncio
async def test_full_pipeline_chat_over_http(tiny_model_dir):
    """End-to-end slice: HTTP -> preprocessor -> backend -> echo core."""
    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir, display_name="tiny")
    engine = build_pipeline_engine(mdc, EchoEngineCore())
    svc = HttpService()
    svc.manager.add_chat_model("tiny", engine)
    svc.manager.add_completion_model("tiny", engine)
    client = await make_client(svc)

    r = await client.post(
        "/v1/chat/completions",
        json={
            "model": "tiny",
            "messages": [{"role": "user", "content": "hello world"}],
            "stream": False,
        },
    )
    assert r.status == 200
    data = await r.json()
    # Echo core streams the prompt tokens back; detokenized text contains
    # the templated prompt, which includes the user message.
    assert "hello world" in data["choices"][0]["message"]["content"]

    r = await client.post(
        "/v1/completions",
        json={"model": "tiny", "prompt": "the quick brown fox", "stream": False},
    )
    assert r.status == 200
    data = await r.json()
    assert "quick brown fox" in data["choices"][0]["text"]
    await client.close()


@pytest.mark.asyncio
async def test_completion_streaming_with_usage(tiny_model_dir):
    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir, display_name="tiny")
    engine = build_pipeline_engine(mdc, EchoEngineCore())
    svc = HttpService()
    svc.manager.add_completion_model("tiny", engine)
    client = await make_client(svc)
    r = await client.post(
        "/v1/completions",
        json={
            "model": "tiny",
            "prompt": "hello",
            "stream": True,
            "stream_options": {"include_usage": True},
        },
    )
    raw = (await r.read()).decode()
    usages = [
        json.loads(line[6:])
        for line in raw.split("\n")
        if line.startswith("data: ") and line != "data: [DONE]"
        if "usage" in line
    ]
    assert any(u.get("usage") for u in usages)
    await client.close()


@pytest.mark.asyncio
async def test_batched_prompts_expand_with_indexed_choices(tiny_model_dir):
    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir, display_name="tiny")
    engine = build_pipeline_engine(mdc, EchoEngineCore())
    svc = HttpService()
    svc.manager.add_completion_model("tiny", engine)
    client = await make_client(svc)
    r = await client.post(
        "/v1/completions",
        json={"model": "tiny", "prompt": ["hello", "world"], "stream": False},
    )
    assert r.status == 200
    data = await r.json()
    assert len(data["choices"]) == 2
    by_index = {c["index"]: c["text"] for c in data["choices"]}
    assert "hello" in by_index[0] and "world" in by_index[1]
    await client.close()


@pytest.mark.asyncio
async def test_prompt_too_long_is_400(tiny_model_dir):
    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir, display_name="tiny")
    mdc.context_length = 4
    engine = build_pipeline_engine(mdc, EchoEngineCore())
    svc = HttpService()
    svc.manager.add_completion_model("tiny", engine)
    client = await make_client(svc)
    r = await client.post(
        "/v1/completions",
        json={"model": "tiny", "prompt": "this prompt is definitely longer than four tokens"},
    )
    assert r.status == 400
    assert (await r.json())["error"]["type"] == "context_length_exceeded"
    await client.close()
