"""G2 host-RAM KV tier + async device↔host copy stream.

Capability parity with the reference's two-tier KV storage manager
(``/root/reference/lib/llm/src/kv/manager.rs:22-168`` — G1 device / G2
host — and the ``CopyStream`` batched async block copies in
``kv/layer.rs:619-2066`` backed by ``kernels/block_copy.cu``), redesigned
for TPU:

- The host tier is one preallocated numpy pool per K/V (the reference
  uses pinned host memory via ``cuda_malloc_host``; on TPU-VM plain
  numpy is already in host RAM and ``jax.device_put`` DMAs from it).
- Device→host movement = a jitted per-page gather (XLA dynamic-slice on
  the page axis) dispatched on the engine loop thread, then materialized
  (``np.asarray``) on a background copy thread so eviction never blocks
  the decode loop. Dispatch-order semantics guarantee the gather reads
  the page before any later donated forward overwrites it.
- Host→device movement = a jitted scatter (``.at[:, pid].set``) of the
  host page into a freshly allocated device page, dispatched before the
  prefill that consumes it.

Pages are keyed by the same chained sequence hash used for G1 prefix
reuse and router events (``tokens.py``), so the three tiers (device,
host, remote-worker-via-router) share one content-addressing scheme.
"""

from __future__ import annotations

import logging
import queue
import threading
from collections import OrderedDict

import numpy as np

log = logging.getLogger(__name__)


class HostKvPool:
    """Fixed-capacity host-RAM page pool, content-addressed, LRU-evicted.

    Thread-safe: written by the copy thread, read (matched/fetched) by
    the engine loop thread.
    """

    def __init__(
        self,
        num_pages: int,
        page_shape: tuple[int, ...],
        dtype,
        on_demote=None,
    ):
        self.num_pages = num_pages
        self._k = np.zeros((num_pages,) + page_shape, dtype)
        self._v = np.zeros((num_pages,) + page_shape, dtype)
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        # seq_hash -> host slot; OrderedDict doubles as the LRU (oldest first).
        self._by_hash: OrderedDict[int, int] = OrderedDict()
        self._lock = threading.Lock()
        # ``on_demote(seq_hash, k_copy, v_copy)``: called with a COPY of
        # each LRU-evicted page's bytes, outside the pool lock — the
        # G2→G3 demotion hook (docs/fault_tolerance.md "Durable KV").
        # Runs on whichever thread triggered the eviction (copy thread
        # for offloads, loop thread for admission promotes); the G3
        # writer never fsyncs per page, so neither stalls.
        self.on_demote = on_demote
        # Metrics.
        self.stores = 0
        self.hits = 0
        self.evictions = 0

    def __contains__(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._by_hash

    @property
    def resident(self) -> int:
        with self._lock:
            return len(self._by_hash)

    def store(self, seq_hash: int, k_page: np.ndarray, v_page: np.ndarray) -> None:
        """Insert one page; evicts the LRU page when full (the victim's
        bytes are copied out and handed to :attr:`on_demote` — cold
        G2 → G3 — before the slot is overwritten). Idempotent per hash
        (a page already resident is refreshed, not duplicated)."""
        demoted = None
        with self._lock:
            slot = self._by_hash.get(seq_hash)
            if slot is None:
                if self._free:
                    slot = self._free.pop()
                else:
                    h_old, slot = self._by_hash.popitem(last=False)
                    self.evictions += 1
                    if self.on_demote is not None:
                        # Copy before the overwrite below; callback fires
                        # outside the lock (it does file I/O).
                        demoted = (
                            h_old, self._k[slot].copy(), self._v[slot].copy()
                        )
                self._by_hash[seq_hash] = slot
            self._by_hash.move_to_end(seq_hash)
            self._k[slot] = k_page
            self._v[slot] = v_page
            self.stores += 1
        if demoted is not None:
            try:
                self.on_demote(*demoted)
            except Exception:  # a broken G3 writer must not break G2
                log.exception("G2->G3 demotion callback failed")

    def fetch(self, seq_hash: int) -> tuple[np.ndarray, np.ndarray] | None:
        """Copy one page out (the copy pins the content against a
        concurrent LRU eviction overwriting the slot)."""
        with self._lock:
            slot = self._by_hash.get(seq_hash)
            if slot is None:
                return None
            self._by_hash.move_to_end(seq_hash)
            self.hits += 1
            return self._k[slot].copy(), self._v[slot].copy()

    def match_chain(self, seq_hashes: list[int]) -> list[int]:
        """Longest resident prefix of the hash chain (for extending a G1
        match into G2 without fetching yet)."""
        out: list[int] = []
        with self._lock:
            for h in seq_hashes:
                if h not in self._by_hash:
                    break
                out.append(h)
        return out

    def snapshot(self) -> list[tuple[int, np.ndarray, np.ndarray]]:
        """Copy every resident page out, LRU-oldest first, without
        touching recency or hit counters — the graceful-shutdown G2→G3
        drain (``TPUEngine.stop``) walks this so the sealed manifest
        covers the whole warm set."""
        with self._lock:
            return [
                (h, self._k[slot].copy(), self._v[slot].copy())
                for h, slot in self._by_hash.items()
            ]


class CopyStream:
    """Background device↔host copy stream.

    Device→host (offload): the engine loop dispatches the on-device
    page gather (cheap, async) and hands the resulting device arrays
    here; this thread blocks on the transfer (``np.asarray``) and
    commits the page into the host pool — the TPU analogue of the
    reference's CUDA ``CopyStream`` with completion events
    (``kv/layer.rs:619+``).

    Host→device (prefetch, docs/engine_perf.md "Predictive KV
    tiering"): :meth:`fetch_batch` copies requested pages *out* of the
    host pool off the engine loop thread and hands them to a callback;
    the engine loop then injects them with the existing batched
    scatter — so a G2→G1 restore's host memcpy overlaps device compute
    instead of serializing the admission path. One bounded queue
    carries both directions, so :meth:`drain` and :meth:`stop` cover
    prefetches exactly like offloads.
    """

    def __init__(self, pool: HostKvPool, max_inflight: int = 256, store=None):
        self.pool = pool
        # Optional G3 PersistentKvStore: fetches that miss G2 fall
        # through to it (checksum-verified there) and promote the bytes
        # back into the host pool, so a G3→G1 restore overlaps compute
        # exactly like a G2→G1 one.
        self.store = store
        # Bounded: each offload entry pins a gathered K/V device-array
        # pair, so a burst of evictions outpacing the blocking host
        # transfers must shed load (the tier is a cache — dropping an
        # offload only costs a future recompute) instead of growing HBM
        # pressure unboundedly. Prefetches shed the same way (the
        # caller releases the target pages and retries later).
        self._q: queue.Queue = queue.Queue(maxsize=max_inflight)
        self._thread = threading.Thread(
            target=self._run, name="kv-copy-stream", daemon=True
        )
        self._running = True
        self.dropped = 0
        self._thread.start()

    @property
    def pending(self) -> int:
        """Queued-but-uncommitted items (both directions) — swap-in
        uses this to tell "write-back still in flight" from a genuine
        host-tier miss."""
        return self._q.unfinished_tasks

    def offload_batch(
        self, seq_hashes: list, k_dev, v_dev, on_synced=None,
        on_stored=None,
    ) -> bool:
        """Coalesced offload: one gathered [L, n, ps, HkvD] K/V pair
        covering ``len(seq_hashes)`` pages (page axis 1). The worker
        materializes the whole batch with ONE host transfer and commits
        page-by-page — an eviction burst costs one dispatch + one sync
        instead of one per page. ``on_synced`` (if given) fires right
        after that existing host transfer completes — the dispatch
        profiler's consume point for the ``offload`` kind, so in-flight
        timing rides the sync the stream was doing anyway; ``on_stored``
        fires after the batch is COMMITTED to the pool (the swap
        record's fetchable-from-host signal). Returns False when the
        stream is saturated and the batch was shed (proactive swap-out
        must then keep the pages resident — its bytes, unlike an
        eviction's, are not recomputable)."""
        try:
            self._q.put_nowait(
                ("offload", list(seq_hashes), k_dev, v_dev, on_synced,
                 on_stored)
            )
            return True
        except queue.Full:
            self.dropped += len(seq_hashes)
            return False

    def fetch_batch(self, seq_hashes: list, ctx, on_fetched) -> bool:
        """G2→G1 direction: copy ``seq_hashes``' pages out of the host
        pool on the copy thread and call ``on_fetched(ctx, fetched)``
        with the ``(hash, k_page, v_page)`` prefix that was resident
        (the walk stops at the first miss — a restored prefix must stay
        chain-contiguous to be matchable). The callback runs ON THE
        COPY THREAD; the engine's implementation just queues the result
        for the loop thread. Returns False when the stream is
        saturated (caller releases the reserved pages and retries)."""
        try:
            self._q.put_nowait(("fetch", list(seq_hashes), ctx, on_fetched))
            return True
        except queue.Full:
            self.dropped += len(seq_hashes)
            return False

    def drain(self, timeout: float = 10.0) -> None:
        """Block until every queued offload has *committed* (tests)."""
        import time

        deadline = time.monotonic() + timeout
        while self._q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.005)

    def stop(self) -> None:
        """Stop the stream. Offloads still queued are discarded — the
        tier is a cache, so shutdown loses nothing but future hits."""
        self._running = False
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # worker is mid-backlog; it re-checks _running per item
        self._thread.join(timeout=10)

    def _run(self) -> None:
        while self._running:
            item = self._q.get()
            try:
                if item is None:
                    return
                if item[0] == "fetch":
                    _, seq_hashes, ctx, on_fetched = item
                    fetched = []
                    for h in seq_hashes:
                        data = self.pool.fetch(h)
                        if data is None and self.store is not None:
                            # G3 fallback: checksum-verified fetch (a
                            # corrupt page quarantines there and stays
                            # None — the chain just shortens). Promote
                            # the survivor into G2 so siblings hit RAM.
                            data = self.store.fetch(h)
                            if data is not None:
                                self.pool.store(h, data[0], data[1])
                        if data is None:
                            break  # chain broken: later pages unmatchable
                        fetched.append((h, data[0], data[1]))
                    try:
                        on_fetched(ctx, fetched)
                    except Exception:  # must not kill the stream
                        log.exception("prefetch on_fetched callback failed")
                    continue
                _, seq_hashes, k_dev, v_dev, on_synced, on_stored = item
                k_np, v_np = np.asarray(k_dev), np.asarray(v_dev)  # dynlint: sync-point(offload copy-thread transfer)
                if on_synced is not None:
                    try:
                        on_synced()
                    except Exception:  # profiling must not break offload
                        log.exception("offload on_synced callback failed")
                for j, h in enumerate(seq_hashes):
                    self.pool.store(h, k_np[:, j], v_np[:, j])
                if on_stored is not None:
                    try:
                        on_stored()
                    except Exception:  # bookkeeping must not break offload
                        log.exception("offload on_stored callback failed")
            except Exception:  # never kill the stream on one bad page
                log.exception("KV copy-stream item %s failed", item[0])
            finally:
                self._q.task_done()
