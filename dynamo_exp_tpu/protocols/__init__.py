"""Protocol types: engine-facing and OpenAI-compatible API surfaces."""

from .aggregator import aggregate_chat_stream, aggregate_completion_stream
from .common import (
    BackendInput,
    FinishReason,
    LLMEngineOutput,
    SamplingOptions,
    StopConditions,
)
from .delta import ChatDeltaGenerator, CompletionDeltaGenerator
from .openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatCompletionResponse,
    ChatMessage,
    CompletionChunk,
    CompletionRequest,
    CompletionResponse,
    Extensions,
    ModelInfo,
    ModelList,
    Usage,
)
from .sse import SseDecoder, decode_sse_stream, encode_done, encode_frame

__all__ = [
    "BackendInput",
    "ChatCompletionChunk",
    "ChatCompletionRequest",
    "ChatCompletionResponse",
    "ChatDeltaGenerator",
    "ChatMessage",
    "CompletionChunk",
    "CompletionDeltaGenerator",
    "CompletionRequest",
    "CompletionResponse",
    "Extensions",
    "FinishReason",
    "LLMEngineOutput",
    "ModelInfo",
    "ModelList",
    "SamplingOptions",
    "SseDecoder",
    "StopConditions",
    "Usage",
    "aggregate_chat_stream",
    "aggregate_completion_stream",
    "decode_sse_stream",
    "encode_done",
    "encode_frame",
]
