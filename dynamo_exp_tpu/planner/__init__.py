"""Dynamic worker scaling (the reference's "planner" component).

Reference parity: ``/root/reference/examples/llm/components/planner.py``
(metric-pull + threshold decision loop) and
``/root/reference/components/planner/src/dynamo/planner/local_connector.py``
(scale actions against the local supervisor).
"""

from .connector import LocalConnector, PlannerConnector
from .planner import Planner, PlannerConfig
from .policy import (
    CatalogEntry,
    Decision,
    PlannerObservation,
    PlannerState,
    ScaleAction,
    SloTargets,
    arm_decode_grace,
    maybe_swap_config,
    plan_step,
    plan_step_slo,
)

__all__ = [
    "Planner",
    "PlannerConfig",
    "PlannerConnector",
    "LocalConnector",
    "PlannerObservation",
    "PlannerState",
    "ScaleAction",
    "CatalogEntry",
    "Decision",
    "SloTargets",
    "arm_decode_grace",
    "maybe_swap_config",
    "plan_step",
    "plan_step_slo",
]
