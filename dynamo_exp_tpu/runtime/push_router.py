"""PushRouter: policy-based dispatch over a Client's live instances.

Capability parity with
``/root/reference/lib/runtime/src/pipeline/network/egress/push_router.rs``:
random / round-robin / direct(instance) / static routing, presented as an
AsyncEngine so routers compose with pipelines. KV-aware routing lives in
:mod:`dynamo_exp_tpu.router` and plugs in via ``RouterMode.DIRECT``.

Fault tolerance (docs/fault_tolerance.md): selection skips draining and
breaker-blocked instances (the client's
:class:`~dynamo_exp_tpu.runtime.health.HealthTracker`); a
**connection/stream-start** failure — the transport refused, or the
stream died before its first frame — is retried with exponential backoff
+ jitter against a *different* instance, up to ``retries`` times and
never past the request's deadline. Once the first frame has arrived the
stream is committed to its instance: mid-stream failures always surface
to the caller (re-issuing could duplicate tokens). In-band error frames
(``EngineError``) are application errors, not transport errors, and are
never retried either.
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import random
from typing import Any, AsyncIterator

from ..telemetry import get_telemetry
from .annotated import Annotated
from .client import Client
from .engine import (
    AsyncEngine,
    AsyncEngineContext,
    DeadlineExceededError,
    ResponseStream,
)
from .transports.base import InstanceInfo


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round-robin"
    DIRECT = "direct"
    STATIC = "static"
    KV = "kv"


class NoInstancesError(ConnectionError):
    pass


class NoHealthyInstancesError(NoInstancesError):
    """Instances exist, but every one is draining, breaker-open, or
    already tried this request — the 503 + Retry-After case."""


class PushRouter(AsyncEngine[dict, Any]):
    """Routes each request to one live instance of a remote endpoint."""

    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.RANDOM,
        ready_wait_s: float = 0.0,
        retries: int = 2,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        rng: random.Random | None = None,
    ):
        self.client = client
        self.mode = mode
        # >0: a request arriving before any instance is discovered waits
        # this long for one instead of failing (ingress/graph startup
        # races); 0 keeps the strict fail-fast default.
        self.ready_wait_s = ready_wait_s
        self.retries = retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        # Injectable rng keeps backoff jitter deterministic under test.
        self.rng = rng or random.Random()
        self._rr = itertools.count()

    @property
    def health(self):
        return self.client.health

    def unavailable_ids(self) -> set[int]:
        """Live instance ids currently excluded from selection."""
        return self.health.unavailable_ids(self.client.instances)

    def _pick(
        self, request: dict, exclude: frozenset[int] | set[int] = frozenset()
    ) -> InstanceInfo:
        instances = self.client.instances
        if not instances:
            raise NoInstancesError("no live instances for endpoint")
        # An explicit target always wins, regardless of mode — KV-aware
        # callers (KvPushRouter) do their own health-filtered selection.
        if "_worker_instance_id" in request:
            try:
                return self.client.instance(int(request["_worker_instance_id"]))
            except KeyError as e:
                # Stale target (lease expired) is a routing error, so callers
                # can retry/503 with one except clause.
                raise NoInstancesError(str(e)) from e
        pool = [
            i
            for i in self.health.filter_available(instances)
            if i.instance_id not in exclude
        ]
        if not pool:
            raise NoHealthyInstancesError(
                f"no healthy instances for endpoint "
                f"({len(instances)} live, all draining/unhealthy/tried)"
            )
        if self.mode is RouterMode.RANDOM:
            return self.rng.choice(pool)
        if self.mode is RouterMode.ROUND_ROBIN:
            return pool[next(self._rr) % len(pool)]
        if self.mode in (RouterMode.DIRECT, RouterMode.KV):
            # The explicit-target branch above handles present ids.
            raise ValueError("direct routing requires _worker_instance_id")
        # STATIC: single fixed instance
        return pool[0]

    async def sleep_backoff(
        self, attempt: int, ctx: AsyncEngineContext
    ) -> None:
        """Exponential backoff with 50% jitter, capped by the deadline.
        Public: KV-aware wrappers reuse this policy for their own
        re-selecting retry loops."""
        delay = min(
            self.backoff_base_s * (2 ** (attempt - 1)), self.backoff_max_s
        )
        delay *= 0.5 + self.rng.random() / 2
        remaining = ctx.time_remaining()
        if remaining is not None:
            delay = min(delay, max(remaining, 0.0))
        if delay > 0:
            await asyncio.sleep(delay)

    async def generate(
        self, request: dict, context: AsyncEngineContext | None = None
    ) -> ResponseStream[Any]:
        ctx = context or AsyncEngineContext()
        if not self.client.instances and self.ready_wait_s > 0:
            try:
                await self.client.wait_for_instances(1, self.ready_wait_s)
            except TimeoutError:
                pass  # fall through to the strict error below
        explicit_target = "_worker_instance_id" in request
        clean = {k: v for k, v in request.items() if k != "_worker_instance_id"}
        tried: set[int] = set()
        attempt = 0
        while True:
            ctx.check_deadline("router")
            instance = self._pick(request, exclude=tried)
            self.health.acquire(instance.instance_id)
            try:
                frames = await self.client.generate_to(instance, clean, ctx)
                first = await _pull_first(frames)
            except ConnectionError as e:
                # Stream-start failure: the instance never produced a
                # frame, so failing over cannot duplicate output.
                self.health.record_failure(instance.instance_id)
                tried.add(instance.instance_id)
                attempt += 1
                if explicit_target or attempt > self.retries:
                    raise
                get_telemetry().request_retries.labels(
                    "connect" if _is_connect_error(e) else "stream_start"
                ).inc()
                await self.sleep_backoff(attempt, ctx)
                continue
            if (
                first is not None
                and first.is_error()
                and ctx.deadline_expired
            ):
                # The deadline expired in transit and the remote plane
                # refused in-band. That is neither an instance failure
                # nor an application error — surface it as the deadline
                # it is (HTTP maps this to 504, not 500).
                raise DeadlineExceededError(
                    first.error_message()
                    or f"request {ctx.id} deadline exceeded at request plane"
                )
            self.health.record_success(instance.instance_id)
            break

        async def _data() -> AsyncIterator[Any]:
            if first is not None:
                if first.is_error():
                    from .client import EngineError

                    raise EngineError(first.error_message() or "remote error")
                if first.data is not None:
                    yield first.data
            async for ann in frames:
                if ann.data is not None:
                    yield ann.data

        return ResponseStream(_data(), ctx)

    async def generate_direct(
        self,
        request: dict,
        instance_id: int,
        context: AsyncEngineContext | None = None,
    ) -> ResponseStream[Any]:
        return await self.generate(
            {**request, "_worker_instance_id": instance_id}, context
        )


async def _pull_first(frames: AsyncIterator[Annotated]) -> Annotated | None:
    """Eagerly pull the stream's first frame so stream-start failures are
    observable inside the retry loop. Error frames are returned (not
    raised): an in-band error means the stream *started* — it is an
    application failure, outside the failover contract. Returns None for
    a clean empty stream."""
    try:
        return await anext(aiter(frames))
    except StopAsyncIteration:
        return None
    except Exception as e:
        # Client.generate_to raises EngineError for error frames; convert
        # the first-frame case back to a frame so the retry loop's
        # ConnectionError filter stays precise.
        from .client import EngineError

        if isinstance(e, EngineError):
            return Annotated.from_error(str(e))
        raise


def _is_connect_error(e: Exception) -> bool:
    """Connect-phase errors mention the transport; stream drops happen
    after dispatch. Best-effort label for the retry counter."""
    return "connect" in str(e).lower() or "no served endpoint" in str(e).lower()
