"""The planner decision loop: pull metrics, scale the worker fleet.

Reference parity: ``/root/reference/examples/llm/components/planner.py``
(lines 51-357) — same signals (average KV-cache load on decode workers,
prefill work-queue depth), same threshold policy, same safeguards:

- scale-down checks run before scale-up (never both directions blind),
- a freshly added decode worker gets a grace period
  (``NEW_DECODE_WORKER_GRACE_PERIOD`` adjustment intervals) before any
  decode scale-down, so its KV cache can populate,
- prefill scale-up only when the queue's linear trend predicts it stays
  above threshold for ``NEW_PREFILL_WORKER_QUEUE_BUFFER_PERIOD``
  intervals (workers take time to start; don't chase spikes),
- a hard chip budget caps the fleet, and fleet-changed-underneath-us
  aborts the adjustment round.

The decision logic itself lives in :mod:`.policy` as the pure,
clock-free ``plan_step`` (and the SLO-driven predictive
``plan_step_slo``, enabled via ``PlannerConfig.slo``); this module is
the asyncio driver that feeds it metrics and applies its actions
through a connector. The cluster simulator (``dynamo_exp_tpu/sim/``)
drives the very same step functions against modeled fleets.

Run standalone against a live graph:

    python -m dynamo_exp_tpu.planner.planner \
        --coordinator HOST:PORT --namespace dynamo \
        --decode-component TpuWorker --prefill-component PrefillWorker
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, replace
from typing import Callable

from .policy import (  # noqa: F401 - re-exported (historic home)
    NEW_DECODE_WORKER_GRACE_PERIOD,
    NEW_PREFILL_WORKER_QUEUE_BUFFER_PERIOD,
    PlannerObservation,
    PlannerState,
    SloTargets,
    arm_decode_grace,
    plan_step,
    plan_step_slo,
)

logger = logging.getLogger(__name__)


@dataclass
class PlannerConfig:
    namespace: str = "dynamo"
    served_model_name: str = "model"
    decode_component: str = "TpuWorker"
    decode_endpoint: str = "generate"
    prefill_component: str = "PrefillWorker"
    metric_pulling_interval: float = 1.0
    adjustment_interval: float = 10.0
    # Chip budget and per-engine chip costs (reference speaks GPUs).
    max_tpu_budget: int = 8
    decode_engine_num_tpu: int = 1
    prefill_engine_num_tpu: int = 1
    min_endpoint: int = 1
    prefill_queue_scale_up_threshold: float = 5.0
    prefill_queue_scale_down_threshold: float = 0.2
    decode_kv_scale_up_threshold: float = 0.9
    decode_kv_scale_down_threshold: float = 0.5
    # Estimated KV fraction one waiting request will claim once admitted
    # (reference planner.py:170 uses the same constant).
    waiting_request_kv_estimate: float = 0.02
    no_operation: bool = False  # observe only
    # SLO-driven predictive mode: when set, decisions come from
    # plan_step_slo (forecast KV/queue trends, size the fleet to p99
    # TTFT/ITL targets) instead of the reactive threshold loop. The live
    # loop feeds it the same queue/KV samples it already collects; the
    # optional p99 measurements ride in where a caller (the simulator,
    # or an embedder with latency histograms) provides them.
    slo: "SloTargets | None" = None
    # Pre-validated tuned configs (policy.CatalogEntry tuple, emitted
    # by ``llmctl tune``): when the live fingerprint drifts past
    # DRIFT_ALERT_THRESHOLD, plan_step_slo swaps to the nearest entry
    # (docs/tuning.md "Catalog swap").
    config_catalog: tuple = ()


class Planner:
    def __init__(
        self,
        drt,
        config: PlannerConfig,
        connector=None,
        clock: Callable[[], float] = time.monotonic,
        slo_source=None,
    ):
        from ..kv_router.metrics_aggregator import KvMetricsAggregator
        from .connector import LocalConnector

        self.drt = drt
        self.cfg = config
        self.connector = connector or LocalConnector(config.namespace, drt)
        # Injected clock: the loop's interval pacing is testable (and the
        # simulator never touches wall time).
        self._clock = clock
        self.metrics_aggregator = KvMetricsAggregator(
            drt.namespace(config.namespace).component(config.decode_component),
            interval_s=config.metric_pulling_interval,
        )
        self.prefill_queue = drt.work_queue(
            prefill_queue_name(config.served_model_name)
        )
        self._decode_client = None
        self._prefill_client = None
        self._plan_state = PlannerState()
        # Per-interval samples.
        self.kv_load: list[float] = []
        self.prefill_queue_load: list[float] = []
        # Optional p99 measurements for the SLO policy, set by an
        # embedder with latency histograms before each adjustment round
        # (cleared with the interval: absent means no signal).
        self.ttft_p99_s: float | None = None
        self.itl_p99_s: float | None = None
        # Fingerprint-plane inputs for the catalog swap, set by an
        # embedder wiring a WorkloadDriftWatch: live drift score vs the
        # pinned reference, and the live fingerprint itself. Unlike the
        # per-interval samples these are NOT reset each round — the
        # drift watch is a continuously maintained signal.
        self.drift_score: float | None = None
        self.live_fingerprint = None
        # SLO attribution source (telemetry.SloAttribution, usually the
        # HTTP edge's): each adjustment round pulls its p99 pressure
        # inputs from the attribution window and resets it — so
        # plan_step_slo is driven by the SAME measurements the goodput/
        # violation counters export, live and in the simulator
        # (docs/observability.md "SLO attribution & goodput").
        self.slo_source = slo_source
        self.adjustments: list[dict] = []  # decision log (tests/observability)
        self._stop = asyncio.Event()

    @property
    def decode_worker_remaining_grace_period(self) -> int:
        return self._plan_state.decode_grace_remaining

    @decode_worker_remaining_grace_period.setter
    def decode_worker_remaining_grace_period(self, value: int) -> None:
        # replace(), not a fresh PlannerState: the legacy setter must
        # not wipe whatever other cross-interval state grows here.
        self._plan_state = replace(
            self._plan_state, decode_grace_remaining=value
        )

    # ------------------------------------------------------------- discovery
    async def get_workers_info(self) -> tuple[list[int], list[int]]:
        """(prefill instance ids, decode instance ids). No prefill fleet
        means aggregated mode (reference: planner.py:86-116)."""
        cfg = self.cfg
        if self._prefill_client is None:
            try:
                ep = (
                    self.drt.namespace(cfg.namespace)
                    .component(cfg.prefill_component)
                    .endpoint("pull")
                )
                self._prefill_client = await ep.client()
            except Exception:
                self._prefill_client = None
        p = (
            self._prefill_client.instance_ids()
            if self._prefill_client is not None
            else []
        )
        if self._decode_client is None:
            ep = (
                self.drt.namespace(cfg.namespace)
                .component(cfg.decode_component)
                .endpoint(cfg.decode_endpoint)
            )
            self._decode_client = await ep.client()
        return p, self._decode_client.instance_ids()

    # --------------------------------------------------------------- metrics
    async def collect_metrics(self) -> None:
        cfg = self.cfg
        try:
            self.prefill_queue_load.append(float(await self.prefill_queue.size()))
        except Exception as e:
            logger.info("prefill queue size unavailable: %s", e)
        endpoints = await self.metrics_aggregator.scrape_once()
        for m in endpoints.metrics.values():
            kv_load = m.gpu_cache_usage_perc
            if m.request_active_slots and m.num_requests_waiting > 0:
                # Waiting requests will claim cache once admitted; bias
                # the signal up so the planner scales before they land.
                kv_load += cfg.waiting_request_kv_estimate * m.num_requests_waiting
            self.kv_load.append(kv_load)

    def _reset_interval(self) -> None:
        self.kv_load = []
        self.prefill_queue_load = []
        # p99s are per-interval measurements like the samples above: a
        # stale breach left in place would read as pressure every round
        # (the same scrape-outage-as-load failure observe() documents).
        self.ttft_p99_s = None
        self.itl_p99_s = None

    # ----------------------------------------------------------- adjustments
    async def make_adjustments(
        self, p_endpoints: list[int], d_endpoints: list[int]
    ) -> None:
        """Re-check the fleet, then apply the policy. Adjustments are
        skipped when the fleet changed underneath the interval
        (reference: planner.py:208-215)."""
        new_p, new_d = await self.get_workers_info()
        if len(new_p) != len(p_endpoints) or len(new_d) != len(d_endpoints):
            logger.info("fleet changed mid-interval; skipping adjustments")
            return
        await self.make_adjustments_with_counts(p_endpoints, d_endpoints)

    def observe(
        self, p_endpoints: list[int], d_endpoints: list[int]
    ) -> PlannerObservation:
        """Package the interval's samples as a pure observation. An
        interval with no samples is NO signal, not zero load: a scrape
        outage (likeliest exactly when workers are saturated) must never
        read as idle and trigger a spurious scale-down. (Reference
        relies on np.mean([]) -> nan failing every comparison; the pure
        policy makes it explicit via Optional means.)"""
        return PlannerObservation(
            num_prefill=len(p_endpoints),
            num_decode=len(d_endpoints),
            prefill_queue=tuple(self.prefill_queue_load),
            kv_load=tuple(self.kv_load),
            ttft_p99_s=self.ttft_p99_s,
            itl_p99_s=self.itl_p99_s,
            now=self._clock(),
            drift_score=self.drift_score,
            fingerprint=self.live_fingerprint,
        )

    async def make_adjustments_with_counts(
        self, p_endpoints: list[int], d_endpoints: list[int]
    ) -> None:
        """Thin driver over the pure policy (public so embedders/tests
        can drive a round without discovery): build the observation,
        take one :func:`plan_step` / :func:`plan_step_slo`, apply each
        proposed action through the connector. The decision logic lives
        in planner/policy.py — shared verbatim with the cluster
        simulator."""
        cfg = self.cfg
        if self.slo_source is not None:
            # Pressure inputs from the shared attribution window; the
            # window resets with the interval exactly like the KV/queue
            # samples (stale breaches must not read as pressure).
            self.ttft_p99_s, self.itl_p99_s = (
                self.slo_source.window_percentiles()
            )
            self.slo_source.reset_window()
        obs = self.observe(p_endpoints, d_endpoints)
        if cfg.slo is not None:
            decision, self._plan_state = plan_step_slo(
                obs, self._plan_state, cfg, cfg.slo
            )
        else:
            decision, self._plan_state = plan_step(
                obs, self._plan_state, cfg
            )
        for note in decision.notes:
            logger.info("%s", note)
        if decision.config_swap is not None:
            self._apply_config_swap(decision.config_swap)
        for action in decision.actions:
            apply = (
                self.connector.add_component
                if action.op == "add"
                else self.connector.remove_component
            )
            if await apply(action.component):
                self._log_action(action.op, action.component, action.signal)
                if (
                    decision.arm_decode_grace
                    and action.op == "add"
                    and action.component == cfg.decode_component
                ):
                    # Only a decode worker that actually spawned earns
                    # scale-down protection.
                    self._plan_state = arm_decode_grace(self._plan_state)

    def _apply_config_swap(self, swap: dict) -> None:
        """Record a catalog swap: adjustment-log entry (the op the sim
        report also carries), ``dynamo_config_swaps_total`` bump, and a
        ``config_swap`` trace span so the flight/trace timeline shows
        when — and why — the fleet changed configs."""
        from ..telemetry import get_telemetry, span

        entry = {
            "op": "config_swap",
            "name": swap["name"],
            "config_hash": swap["config_hash"],
            "drift_before": swap["drift_before"],
            "drift_after": swap["drift_after"],
        }
        self.adjustments.append(entry)
        logger.info("planner action: %s", entry)
        get_telemetry().config_swaps.inc()
        with span(
            "config_swap",
            name=swap["name"],
            config_hash=swap["config_hash"],
            drift_before=swap["drift_before"],
            drift_after=swap["drift_after"],
        ):
            pass

    def _log_action(self, op: str, component: str, signal: float) -> None:
        entry = {"op": op, "component": component, "signal": round(signal, 4)}
        self.adjustments.append(entry)
        logger.info("planner action: %s", entry)

    # ------------------------------------------------------------------ loop
    async def run(self) -> None:
        cfg = self.cfg
        p_endpoints, d_endpoints = await self.get_workers_info()
        self._reset_interval()
        last_adjustment = self._clock()
        while not self._stop.is_set():
            try:
                await self.collect_metrics()
                if (
                    self._clock() - last_adjustment
                    >= cfg.adjustment_interval
                ):
                    if not cfg.no_operation:
                        await self.make_adjustments(p_endpoints, d_endpoints)
                    p_endpoints, d_endpoints = await self.get_workers_info()
                    self._reset_interval()
                    last_adjustment = self._clock()
            except Exception:
                # A transient control-plane error (coordinator blip,
                # scrape failure) must not kill the scaling loop; retry
                # next interval.
                logger.exception("planner round failed; will retry")
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=cfg.metric_pulling_interval
                )
            except asyncio.TimeoutError:
                pass

    def stop(self) -> None:
        self._stop.set()


def prefill_queue_name(model_name: str) -> str:
    """Shared naming for the remote-prefill work queue (reference keys
    its NATS stream by served model name, planner.py:61)."""
    return f"prefill-{model_name}"


def main() -> None:  # pragma: no cover - CLI entry
    import argparse

    from ..runtime.component import DistributedRuntime
    from ..runtime.config import RuntimeConfig

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator", required=True)
    defaults = PlannerConfig()
    for f in (
        "namespace",
        "served_model_name",
        "decode_component",
        "decode_endpoint",
        "prefill_component",
    ):
        p.add_argument(
            f"--{f.replace('_', '-')}", default=getattr(defaults, f)
        )
    for f in (
        "metric_pulling_interval",
        "adjustment_interval",
        "prefill_queue_scale_up_threshold",
        "prefill_queue_scale_down_threshold",
        "decode_kv_scale_up_threshold",
        "decode_kv_scale_down_threshold",
    ):
        p.add_argument(
            f"--{f.replace('_', '-')}",
            type=float,
            default=getattr(defaults, f),
        )
    for f in (
        "max_tpu_budget",
        "decode_engine_num_tpu",
        "prefill_engine_num_tpu",
        "min_endpoint",
    ):
        p.add_argument(
            f"--{f.replace('_', '-')}", type=int, default=getattr(defaults, f)
        )
    p.add_argument("--no-operation", action="store_true")
    args = p.parse_args()

    cfg = PlannerConfig(
        **{
            k: v
            for k, v in vars(args).items()
            if k != "coordinator" and hasattr(defaults, k)
        }
    )

    async def run():
        drt = DistributedRuntime(
            config=RuntimeConfig(coordinator_endpoint=args.coordinator)
        )
        planner = Planner(drt, cfg)
        await planner.run()

    logging.basicConfig(level="INFO")
    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
