"""The compile lattice, enumerated offline (docs/aot.md).

The engine's ONE compiled-program cache (docs/engine_perf.md "One
ragged dispatch") keys every device program by

    (total padded query tokens, static page bound, windowed?,
     full-vs-greedy sampler, want_lp, with_spec)

plus the O(log Pmax) page-move gather/scatter bucket family. This
module derives the *complete reachable set* of those keys from an
:class:`~dynamo_exp_tpu.engine.config.EngineConfig` — every bucket the
``*_bucket_for`` helpers can emit, crossed with the boolean axes — as a
deterministic, hashable :class:`CompileManifest`.

One source of truth: :func:`resolve_ragged_key` is called by the
engine's ``_ragged_fn`` for every live dispatch AND by the enumeration
here, so the manifest cannot drift from what the loop dispatches — a
key the engine computes that the lattice failed to enumerate is a
regression the variant-count guard in ``tests/test_ragged_attention.py``
pins.

Everything here is pure (config in, manifest out): no wall clocks, no
``id()``/``uuid``, no environment — the manifest hash must be
byte-identical across processes and hosts for the same
(model, mesh, knobs, jax version) tuple, because it IS the cache
invalidation rule (docs/aot.md "Cache keying & invalidation").
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field


def resolve_ragged_key(
    cfg,
    attn_impl: str,
    nb: int,
    attn_pages: int | None,
    windowed: bool,
    full_sampler: bool,
    want_lp: bool,
    with_spec: bool = False,
) -> tuple:
    """The ragged variant key for one dispatch shape — the engine's
    ``_ragged_fn`` keying rule, extracted so offline enumeration and
    live dispatch share it verbatim.

    ``attn_impl`` is the engine's *resolved* implementation
    (``TPUEngine._attn_impl``). Two wrinkles live here: short contexts
    (<= ~1k tokens of page bucket) take the XLA gather even when the
    Pallas kernel is available (its serial per-row DMA grid costs more
    than the trivial gather saves) — but only under ``auto``, an
    explicit ``pallas`` is honored; and on the Pallas path the page
    bound vanishes from the key entirely (the kernel DMAs true
    lengths), which is what deletes the page axis from the TPU
    lattice."""
    impl = attn_impl
    if (
        impl == "pallas"
        and cfg.attention_impl == "auto"
        and attn_pages * cfg.page_size <= 1024
    ):
        impl = "xla"
    pages = None if impl == "pallas" else attn_pages
    return (nb, pages, windowed, full_sampler, want_lp, with_spec)


def impl_for_key(key: tuple) -> str:
    """The attention implementation a key's program must be built with:
    a ``None`` page bound is definitionally the Pallas path (it is how
    the bound left the key), anything else is the bounded XLA gather."""
    return "pallas" if key[1] is None else "xla"


# ------------------------------------------------------------ bucket spans
def _pow2_candidates(cap: int) -> list[int]:
    """1, 2, 4, ... up to (and including) ``cap`` — probe points that
    hit every reachable output of a ``_pow2_bucket``-based helper."""
    out, n = [], 1
    while n < cap:
        out.append(n)
        n *= 2
    out.append(max(cap, 1))
    return out


def windowed_token_buckets(cfg) -> list[int]:
    """Every token bucket a pure-decode windowed dispatch can key on
    (1/2/4/.../max_decode_slots, capped at the slot envelope)."""
    return sorted(
        {
            cfg.ragged_tokens_bucket_for(n)
            for n in _pow2_candidates(cfg.max_decode_slots)
        }
    )


def mixed_token_buckets(cfg) -> list[int]:
    """Every flat-stream token bucket a mixed dispatch can key on
    (16-floored powers of two up to ``ragged_max_tokens``)."""
    return sorted(
        {
            cfg.ragged_tokens_bucket_for(n, mixed=True)
            for n in _pow2_candidates(cfg.ragged_max_tokens)
        }
    )


def page_bound_buckets(cfg) -> list[int]:
    """Every static page bound the XLA attention gather can key on."""
    return sorted(
        {
            cfg.ragged_page_bucket_for(p)
            for p in _pow2_candidates(cfg.max_pages_per_seq)
        }
    )


def page_move_buckets(cfg) -> list[int]:
    """Every batched gather/scatter bucket the kv_move/offload family
    can key on. Per-sequence moves (disagg extract/inject, G2 uploads)
    are bounded by ``max_pages_per_seq``, but ``_flush_offloads``
    coalesces eviction bursts ACROSS sequences — one reclaim sweep can
    evict up to the whole pool — so the family is enumerated to
    ``num_pages`` (each extra bucket is one tiny gather/scatter
    compile; missing one would put an inline compile back on a
    warm-booted serving path)."""
    cap = max(cfg.num_pages, cfg.max_pages_per_seq)
    return sorted(
        {cfg.page_move_bucket_for(p) for p in _pow2_candidates(cap)}
    )


# --------------------------------------------------------------- variants
@dataclass(frozen=True)
class RaggedVariant:
    """One ragged compile-lattice entry (== one ``_ragged_fns`` key).
    ``pages=None`` is the Pallas path (no static page bound)."""

    nb: int
    pages: int | None
    windowed: bool
    full_sampler: bool
    want_lp: bool
    with_spec: bool

    @property
    def key(self) -> tuple:
        return (
            self.nb,
            self.pages,
            self.windowed,
            self.full_sampler,
            self.want_lp,
            self.with_spec,
        )

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RaggedVariant":
        return cls(
            nb=int(d["nb"]),
            pages=None if d.get("pages") is None else int(d["pages"]),
            windowed=bool(d["windowed"]),
            full_sampler=bool(d["full_sampler"]),
            want_lp=bool(d["want_lp"]),
            with_spec=bool(d["with_spec"]),
        )


def ragged_variants(
    cfg,
    attn_impl: str,
    include_lp: bool = True,
    include_spec: bool | None = None,
) -> list[RaggedVariant]:
    """Enumerate the full reachable ragged lattice, deduplicated through
    :func:`resolve_ragged_key` (the Pallas page-bound collapse and the
    small-bucket XLA downgrade both fold enumeration points together
    exactly as they fold live dispatches together).

    ``include_lp=False`` halves the lattice for deployments that never
    serve logprobs; ``include_spec`` defaults to whether the config has
    speculation on (draft-carrying variants only exist then)."""
    if include_spec is None:
        include_spec = cfg.spec_mode != "off"
    lp_axis = (False, True) if include_lp else (False,)
    seen: dict[tuple, RaggedVariant] = {}
    for windowed, nb_buckets in (
        (True, windowed_token_buckets(cfg)),
        (False, mixed_token_buckets(cfg)),
    ):
        spec_axis = (
            (False, True) if (include_spec and not windowed) else (False,)
        )
        for nb in nb_buckets:
            for pages in page_bound_buckets(cfg):
                for full_sampler in (False, True):
                    for want_lp in lp_axis:
                        for with_spec in spec_axis:
                            key = resolve_ragged_key(
                                cfg, attn_impl, nb, pages, windowed,
                                full_sampler, want_lp, with_spec,
                            )
                            if key not in seen:
                                seen[key] = RaggedVariant(*key)
    return sorted(
        seen.values(),
        key=lambda v: (
            not v.windowed,
            v.nb,
            -1 if v.pages is None else v.pages,
            v.full_sampler,
            v.want_lp,
            v.with_spec,
        ),
    )


# --------------------------------------------------------------- manifest
_SCHEMA = 1


@dataclass
class CompileManifest:
    """The deterministic compile-lattice artifact (docs/aot.md).

    ``hash()`` is the cache-invalidation key: it covers everything that
    changes compiled-program bytes or the lattice itself — the model
    config, the mesh shape, the lattice-shaping engine knobs, and the
    jax version. Two processes given the same inputs produce
    byte-identical manifests (and hashes); anything else is a bug the
    determinism tests pin."""

    model: dict
    mesh: dict
    engine: dict
    jax_version: str
    ragged: list[RaggedVariant] = field(default_factory=list)
    move_buckets: list[int] = field(default_factory=list)
    schema: int = _SCHEMA

    def to_dict(self) -> dict:
        return {
            "schema": self.schema,
            "model": self.model,
            "mesh": self.mesh,
            "engine": self.engine,
            "jax_version": self.jax_version,
            "ragged": [v.to_dict() for v in self.ragged],
            "move_buckets": list(self.move_buckets),
        }

    def to_json(self, indent: int | None = None) -> str:
        # sort_keys + no whitespace variance: the serialized form is
        # the hashed form, so it must be canonical.
        return json.dumps(self.to_dict(), sort_keys=True, indent=indent)

    @classmethod
    def from_dict(cls, d: dict) -> "CompileManifest":
        return cls(
            model=dict(d["model"]),
            mesh=dict(d["mesh"]),
            engine=dict(d["engine"]),
            jax_version=str(d["jax_version"]),
            ragged=[RaggedVariant.from_dict(v) for v in d["ragged"]],
            move_buckets=[int(b) for b in d["move_buckets"]],
            schema=int(d.get("schema", _SCHEMA)),
        )

    @classmethod
    def from_json(cls, text: str) -> "CompileManifest":
        return cls.from_dict(json.loads(text))

    def hash(self) -> str:
        return hashlib.sha256(self.to_json().encode()).hexdigest()

    def ragged_keys(self) -> set[tuple]:
        return {v.key for v in self.ragged}

    def __len__(self) -> int:
        return len(self.ragged) + len(self.move_buckets)


def _model_fingerprint(mcfg) -> dict:
    """Every ModelConfig field, JSON-normalized — a changed head count
    or dtype must change the manifest hash."""
    out = {}
    for k, v in sorted(asdict(mcfg).items()):
        out[k] = list(v) if isinstance(v, tuple) else v
    return out


def _engine_fingerprint(cfg, attn_impl: str, interpret: bool) -> dict:
    """The EngineConfig knobs that shape compiled-program bytes or the
    lattice: pool/envelope shapes, bucket policies, the resolved
    attention implementation, and the speculation axis."""
    return {
        "max_decode_slots": cfg.max_decode_slots,
        "page_size": cfg.page_size,
        "num_pages": cfg.num_pages,  # KV pool shape is in every program
        "max_model_len": cfg.max_model_len,
        "prefill_chunk": cfg.prefill_chunk,
        "decode_window": cfg.decode_window,
        "device_stop_width": cfg.device_stop_width,
        "kv_dtype": cfg.kv_dtype,
        "attention_impl": attn_impl,
        "pallas_interpret": interpret,
        "ragged_q_tile": cfg.ragged_q_tile,
        "spec_on": cfg.spec_mode != "off",
        "spec_max_draft": cfg.spec_max_draft,
    }


def build_manifest(
    cfg,
    attn_impl: str,
    mesh_shape: dict,
    jax_version: str,
    interpret: bool = False,
    include_lp: bool = True,
    include_spec: bool | None = None,
) -> CompileManifest:
    """Enumerate the full compile lattice for one engine shape.

    ``attn_impl`` must be the engine's *resolved* implementation (the
    ``auto`` decision depends on the device platform, which is part of
    what the manifest pins); ``mesh_shape`` is the engine mesh's
    ``dict(mesh.shape)``."""
    return CompileManifest(
        model=_model_fingerprint(cfg.model),
        mesh={k: int(v) for k, v in sorted(mesh_shape.items())},
        engine=_engine_fingerprint(cfg, attn_impl, interpret),
        jax_version=jax_version,
        ragged=ragged_variants(
            cfg, attn_impl, include_lp=include_lp, include_spec=include_spec
        ),
        move_buckets=page_move_buckets(cfg),
    )
