"""Tests for tokenizer streaming decode, chat templating, preprocessor,
and the detokenizing backend with stop handling."""

import pytest

from dynamo_exp_tpu.backend import Backend, StopSequenceJail
from dynamo_exp_tpu.engines.echo import EchoEngineCore
from dynamo_exp_tpu.model_card import ModelDeploymentCard
from dynamo_exp_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_exp_tpu.protocols import (
    BackendInput,
    ChatCompletionRequest,
    FinishReason,
    LLMEngineOutput,
    StopConditions,
)
from dynamo_exp_tpu.tokenizer import Tokenizer


# --- tokenizer ---------------------------------------------------------
def test_decode_stream_reassembles_text(tiny_model_dir):
    tok = Tokenizer.from_pretrained(tiny_model_dir)
    text = "hello world café 日本語 snowman"
    ids = tok.encode(text, add_special_tokens=False).ids
    stream = tok.decode_stream()
    out = "".join(p for p in (stream.step(t) for t in ids) if p)
    assert out == text


def test_eos_ids_loaded_from_config(tiny_model_dir):
    tok = Tokenizer.from_pretrained(tiny_model_dir)
    assert tok.eos_token_ids == [1]


# --- chat template -----------------------------------------------------
def test_prompt_formatter_renders_template(tiny_model_dir):
    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir)
    fmt = PromptFormatter(mdc)
    out = fmt.render(
        [
            {"role": "system", "content": "be nice"},
            {"role": "user", "content": "hi"},
        ]
    )
    assert out == "<|system|>be nice</s><|user|>hi</s><|assistant|>"


def test_prompt_formatter_fallback_without_template():
    mdc = ModelDeploymentCard(display_name="x")
    out = PromptFormatter(mdc).render([{"role": "user", "content": "hi"}])
    assert "user: hi" in out and out.endswith("assistant:")


# --- stop jail ---------------------------------------------------------
def test_stop_jail_hides_full_stop_sequence():
    jail = StopSequenceJail(["STOP"])
    safe, matched = jail.feed("hello ST")
    assert safe == "hello " and not matched
    safe, matched = jail.feed("OP world")
    assert safe == "" and matched


def test_stop_jail_releases_diverging_prefix():
    jail = StopSequenceJail(["STOP"])
    safe, matched = jail.feed("a ST")
    assert safe == "a " and not matched
    safe, matched = jail.feed("ART")  # "STA"... diverges from "STOP" at 'A'
    assert safe == "START"[:-1] + "T" or safe == "START"  # released in full
    assert not matched
    assert jail.flush() == ""


def test_stop_jail_flush_releases_tail():
    jail = StopSequenceJail(["STOP"])
    safe, _ = jail.feed("end with S")
    assert safe == "end with "
    assert jail.flush() == "S"


# --- preprocessor ------------------------------------------------------
def test_preprocess_chat_builds_backend_input(tiny_model_dir):
    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir)
    pre = OpenAIPreprocessor(mdc)
    req = ChatCompletionRequest.model_validate(
        {
            "model": "tiny",
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 5,
            "stop": ["END"],
        }
    )
    binput = pre.preprocess_chat(req)
    assert len(binput.token_ids) > 0
    assert binput.stop_conditions.max_tokens == 5
    assert binput.stop_conditions.stop == ["END"]
    # EOS ids filled from the model card.
    assert binput.stop_conditions.stop_token_ids == [1]
    # Round-trips through the tokenizer to the rendered prompt.
    assert "hello world" in pre.tokenizer.decode(binput.token_ids)


def test_preprocess_default_max_tokens_fills_context(tiny_model_dir):
    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir)
    pre = OpenAIPreprocessor(mdc)
    req = ChatCompletionRequest.model_validate(
        {"model": "tiny", "messages": [{"role": "user", "content": "hi"}]}
    )
    binput = pre.preprocess_chat(req)
    assert (
        binput.stop_conditions.max_tokens
        == mdc.context_length - len(binput.token_ids)
    )


# --- backend -----------------------------------------------------------
@pytest.mark.asyncio
async def test_backend_detokenizes_echo_stream(tiny_model_dir):
    tok = Tokenizer.from_pretrained(tiny_model_dir)
    backend = Backend(EchoEngineCore(), tok)
    text = "the quick brown fox"
    ids = tok.encode(text, add_special_tokens=False).ids
    binput = BackendInput(
        token_ids=ids, stop_conditions=StopConditions(max_tokens=100)
    )
    stream = await backend.generate(binput)
    pieces, finish = [], None
    async for item in stream:
        out = LLMEngineOutput.from_dict(item)
        if out.text:
            pieces.append(out.text)
        if out.finish_reason:
            finish = out.finish_reason
    assert "".join(pieces) == text
    assert finish == FinishReason.LENGTH


@pytest.mark.asyncio
async def test_backend_stops_on_eos_token(tiny_model_dir):
    tok = Tokenizer.from_pretrained(tiny_model_dir)
    backend = Backend(EchoEngineCore(), tok)
    ids = tok.encode("hello", add_special_tokens=False).ids
    # Inject EOS (id 1) mid-stream.
    binput = BackendInput(
        token_ids=[ids[0], 1] + ids[1:],
        stop_conditions=StopConditions(max_tokens=100, stop_token_ids=[1]),
    )
    stream = await backend.generate(binput)
    outs = [LLMEngineOutput.from_dict(i) async for i in stream]
    assert outs[-1].finish_reason == FinishReason.EOS
    # Nothing after EOS was emitted.
    text = "".join(o.text or "" for o in outs)
    assert "hello"[1:] not in text or text == ""


@pytest.mark.asyncio
async def test_backend_hidden_stop_string(tiny_model_dir):
    tok = Tokenizer.from_pretrained(tiny_model_dir)
    backend = Backend(EchoEngineCore(), tok)
    ids = tok.encode("hello STOP world", add_special_tokens=False).ids
    binput = BackendInput(
        token_ids=ids,
        stop_conditions=StopConditions(max_tokens=100, stop=["STOP"]),
    )
    stream = await backend.generate(binput)
    outs = [LLMEngineOutput.from_dict(i) async for i in stream]
    text = "".join(o.text or "" for o in outs)
    assert "STOP" not in text
    assert "world" not in text
    assert text.startswith("hello")
    assert outs[-1].finish_reason == FinishReason.STOP


@pytest.mark.asyncio
async def test_backend_max_tokens(tiny_model_dir):
    tok = Tokenizer.from_pretrained(tiny_model_dir)
    backend = Backend(EchoEngineCore(), tok)
    ids = tok.encode("the quick brown fox jumps", add_special_tokens=False).ids
    binput = BackendInput(
        token_ids=ids, stop_conditions=StopConditions(max_tokens=2)
    )
    stream = await backend.generate(binput)
    outs = [LLMEngineOutput.from_dict(i) async for i in stream]
    assert outs[-1].finish_reason == FinishReason.LENGTH
    assert outs[-1].completion_tokens == 2


@pytest.mark.asyncio
async def test_backend_flushes_jailed_text_on_length_finish(tiny_model_dir):
    """Regression: text held as a possible stop-prefix must be released
    when generation ends without the stop string completing."""
    tok = Tokenizer.from_pretrained(tiny_model_dir)
    backend = Backend(EchoEngineCore(), tok)
    text = "end with S"
    ids = tok.encode(text, add_special_tokens=False).ids
    binput = BackendInput(
        token_ids=ids,
        stop_conditions=StopConditions(max_tokens=len(ids), stop=["STOP"]),
    )
    stream = await backend.generate(binput)
    pieces = []
    async for i in stream:
        pieces.append(LLMEngineOutput.from_dict(i).text or "")
    assert "".join(pieces) == text  # trailing "S" not swallowed
