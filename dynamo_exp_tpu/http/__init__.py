"""OpenAI-compatible HTTP ingress."""

from .metrics import ServiceMetrics
from .service import HttpService, ModelManager, build_pipeline_engine

__all__ = ["HttpService", "ModelManager", "ServiceMetrics", "build_pipeline_engine"]
