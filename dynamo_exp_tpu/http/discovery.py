"""Ingress model discovery: watch ``models/``, build serving chains.

Capability parity with the reference's ModelWatcher
(``/root/reference/lib/llm/src/http/service/discovery.rs:100-340``): on a
new ModelEntry, fetch the ModelDeploymentCard from the object store and
register a preprocessor→backend→router chain with the ModelManager; on
removal (lease expiry = worker death), drop the model.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging

from ..local_model import MDC_BUCKET, MODELS_PREFIX, ModelEntry
from ..model_card import ModelDeploymentCard
from ..runtime.component import DistributedRuntime
from ..runtime.push_router import RouterMode
from ..runtime.transports.base import EndpointAddress
from .service import ModelManager, build_pipeline_engine

logger = logging.getLogger(__name__)


class ModelWatcher:
    """Keeps a ModelManager in sync with the discovery KV's ``models/``."""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.RANDOM,
    ):
        self.drt = drt
        self.manager = manager
        self.router_mode = router_mode
        self._task: asyncio.Task | None = None
        # Reconciled state. Bindings map each served surface —
        # (name, "chat"/"completion") — to the serving identity it is
        # currently routed through. Chains/routers are keyed by that
        # identity — (name, endpoint, mdc_key) — NOT by name alone: one
        # name's chat and completion entries may point at different
        # workers, and each surface's traffic must ride its own entry's
        # chain.
        self._bindings: dict[tuple[str, str], tuple] = {}
        self._kv_routers: dict[tuple, object] = {}
        self._chains: dict[tuple, object] = {}

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._watch())
        self._sweep_task = asyncio.ensure_future(self._sweep_expired_cards())

    async def close(self) -> None:
        for attr in ("_task", "_sweep_task"):
            task = getattr(self, attr, None)
            if task is not None:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await task
                setattr(self, attr, None)
        for r in self._kv_routers.values():
            await r.stop()
        self._kv_routers.clear()

    async def _watch(self) -> None:
        # The watch stream itself can break (coordinator hiccup); an
        # ingress must re-establish it, not freeze its model set.
        while True:
            try:
                async for snapshot in self.drt.discovery.kv_watch_prefix(
                    MODELS_PREFIX
                ):
                    try:
                        await self._apply(snapshot)
                    except Exception:  # noqa: BLE001 - keep watching
                        logger.exception("model watch apply failed")
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - reconnect after backoff
                logger.exception("model watch stream broke; retrying")
                await asyncio.sleep(1.0)

    async def _sweep_expired_cards(self, period_s: float | None = None) -> None:
        """Delete cards whose heartbeat went stale (reference: model.rs
        expiry watcher, checked every CARD_MAX_AGE/3). The worker-side
        purge is best-effort — two replicas closing simultaneously can
        each skip deletion seeing the other's entry — so ingress owns
        the authoritative sweep; ``is_expired`` at fetch time fences any
        card a sweep hasn't reached yet."""
        from ..model_card import CARD_MAX_AGE_S

        if period_s is None:
            period_s = CARD_MAX_AGE_S / 3
        while True:
            await asyncio.sleep(period_s)
            try:
                for key in await self.drt.object_store.list(MDC_BUCKET):
                    raw = await self.drt.object_store.get(MDC_BUCKET, key)
                    if raw is None:
                        continue
                    try:
                        card = ModelDeploymentCard.from_json(raw.decode())
                    except Exception:  # noqa: BLE001 - unreadable card:
                        continue  # leave for an operator to inspect
                    if card.is_expired():
                        # Re-fetch immediately before deleting: a worker
                        # heartbeat landing between the first read and
                        # the delete re-stamps the card, and deleting on
                        # the stale copy would sweep a live model. The
                        # narrow re-check window can't fully close the
                        # race (the store has no compare-and-delete) but
                        # the heartbeat re-publishes every
                        # CARD_MAX_AGE_S/3, so a lost card outlives one
                        # period at most.
                        raw = await self.drt.object_store.get(MDC_BUCKET, key)
                        if raw is None:
                            continue
                        try:
                            card = ModelDeploymentCard.from_json(raw.decode())
                        except Exception:  # noqa: BLE001 - unreadable now:
                            continue  # leave for an operator to inspect
                        if not card.is_expired():
                            continue  # heartbeat won the race; keep it
                        await self.drt.object_store.delete(MDC_BUCKET, key)
                        logger.info("swept expired model card %s", key)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - retry next period
                logger.exception("model card sweep failed")

    @staticmethod
    def _types_of(model_type: str) -> set[str]:
        return {"chat", "completion"} if model_type == "both" else {model_type}

    async def _apply(self, snapshot: dict[str, bytes]) -> None:
        """Reconcile served surfaces with the snapshot, declaratively.

        Desired state is recomputed from scratch each time: for every
        (name, type) surface, the first live entry (sorted by KV key,
        deterministic) provides the serving identity. Diffing desired
        against current bindings handles every transition in one place
        — add, last-replica removal, AND identity churn (a worker
        re-registering with a new endpoint or model card rebinds the
        surface to the new identity instead of freezing on the old).
        """
        desired: dict[tuple[str, str], tuple] = {}
        entries_by_identity: dict[tuple, ModelEntry] = {}
        for key in sorted(snapshot):
            try:
                entry = ModelEntry.from_bytes(snapshot[key])
            except Exception:  # noqa: BLE001 - one bad entry: skip it
                logger.exception("undecodable model entry %s", key)
                continue
            ident = (entry.name, entry.endpoint, entry.mdc_key)
            entries_by_identity.setdefault(ident, entry)
            for t in self._types_of(entry.model_type):
                desired.setdefault((entry.name, t), ident)

        # Bind new/changed surfaces. Per-surface guard: one bad entry
        # (missing MDC, unreadable tokenizer) must not block siblings.
        for surface, ident in desired.items():
            if self._bindings.get(surface) == ident:
                continue
            try:
                engine = self._chains.get(ident)
                if engine is None:
                    engine = await self._build_chain(entries_by_identity[ident])
                    self._chains[ident] = engine
                name, t = surface
                if t == "chat":
                    self.manager.add_chat_model(name, engine)
                else:
                    self.manager.add_completion_model(name, engine)
                self._bindings[surface] = ident
                logger.info("model %s (%s) bound to %s", name, t, ident[1])
            except Exception:  # noqa: BLE001 - retried on next KV change
                logger.exception("failed to bind %s to %s", surface, ident)

        # Unbind surfaces with no live entry left.
        for surface in [s for s in self._bindings if s not in desired]:
            name, t = surface
            if t == "chat":
                self.manager.remove_chat_model(name)
            else:
                self.manager.remove_completion_model(name)
            del self._bindings[surface]
            logger.info("model %s (%s) removed (last worker gone)", name, t)

        # Tear down chains/routers no surface routes through anymore
        # (identity died, or a rebind moved its surfaces elsewhere).
        in_use = set(self._bindings.values())
        for ck in [k for k in self._chains if k not in in_use]:
            del self._chains[ck]
        for rk in [k for k in self._kv_routers if k not in in_use]:
            router = self._kv_routers.pop(rk)
            await router.stop()  # drop its event sub + scrape loop

    async def _build_chain(self, entry: ModelEntry):
        raw = await self.drt.object_store.get(MDC_BUCKET, entry.mdc_key)
        if raw is None:
            raise RuntimeError(f"no MDC in object store for {entry.name}")
        mdc = ModelDeploymentCard.from_json(raw.decode())
        if mdc.is_expired():
            # Heartbeats re-stamp every CARD_MAX_AGE_S/3; a stale stamp
            # means every publisher of this card is gone (the ModelEntry
            # that led us here is a leftover about to be swept). Never
            # build a serving chain from a dead worker's card.
            raise RuntimeError(
                f"model card for {entry.name} expired "
                f"(last published {mdc.last_published})"
            )
        addr = EndpointAddress.from_url(entry.endpoint)
        ep = (
            self.drt.namespace(addr.namespace)
            .component(addr.component)
            .endpoint(addr.name)
        )
        from ..kv_router.router import build_routed_core

        core, kv_router = await build_routed_core(
            ep, self.router_mode, mdc.kv_cache_block_size
        )
        if kv_router is not None:
            # A retry after a partially-failed registration may rebuild
            # the chain; stop the superseded router or it scrapes forever.
            rk = (entry.name, entry.endpoint, entry.mdc_key)
            old = self._kv_routers.pop(rk, None)
            if old is not None:
                await old.stop()
            self._kv_routers[rk] = kv_router
        return build_pipeline_engine(mdc, core)
