"""Request anatomy + workload fingerprint plane (docs/observability.md
"Request anatomy" / "Workload fingerprint").

Covers the PR 16 acceptance surface: span-sweep decomposition
determinism and the component-sum == edge-latency invariant (synthetic
trees, the checked-in fixture, and a live tiny-engine run), flight-dump
reconstruction, fingerprint digest bit-identity across feed orders,
the fingerprint→sim replay round-trip, multi-window SLO burn rates,
the drift watch + fleet rollup, and the new llmctl surfaces.
"""

import asyncio
import contextlib
import io
import json
import os
import random

import pytest

from dynamo_exp_tpu import llmctl
from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput
from dynamo_exp_tpu.telemetry import (
    COMPONENTS,
    AnatomyRing,
    FingerprintBuilder,
    RequestAnatomy,
    Span,
    WorkloadDriftWatch,
    anatomy_from_flight,
    anatomy_from_spans,
    anatomy_from_timing,
    drift_score,
    fingerprint_from_spans,
    load_spans,
    render_anatomy,
    render_slow,
    replay_workload,
)

PS = 8
FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "anatomy_trace.jsonl"
)
# The checked-in fixture's fingerprint digest, pinned: bucketing or
# hashing changes must land with a deliberate update here AND in the
# anatomy-smoke CI job's expectations (docs/observability.md).
FIXTURE_DIGEST = "cc4c9acebff3d398e80362e750157f64"


def _span(stage, trace, sid, start, end, parent="", **attrs):
    return Span(
        stage=stage, trace_id=trace, span_id=sid,
        parent_span_id=parent, start=start, end=end, attrs=attrs,
    )


def _synthetic_trace():
    """One request: queue 0.2s, prefill 1.0s (0.3s compile), transfer
    0.1s inside prefill, decode 1.5s (0.2s swap stall), 0.3s edge
    overhead -> 3.0s total."""
    t = "t" * 32
    return [
        _span("http_request", t, "r", 0.0, 3.0,
              request_id="req-x", ttft_s=1.3, latency_s=3.0, priority=1),
        _span("queue_wait", t, "q", 0.1, 0.3, parent="r"),
        _span("prefill", t, "f", 0.3, 1.3, parent="r",
              prompt_tokens=256, cached_tokens=0, compile_s=0.3),
        _span("kv_transfer_send", t, "s", 1.2, 1.3, parent="f"),
        _span("decode", t, "d", 1.3, 2.8, parent="r",
              generated_tokens=32, priority=1, pages=6, swap_stall_s=0.2),
    ]


# ------------------------------------------------------------ span sweep
def test_sweep_decomposition_sums_to_edge_exactly():
    a = anatomy_from_spans(_synthetic_trace())
    assert a is not None
    assert set(a.components) == set(COMPONENTS)
    assert a.total_s == pytest.approx(a.edge_latency_s, abs=1e-6)
    assert a.edge_latency_s == pytest.approx(3.0)
    # The transfer span's claim wins its instants away from prefill.
    assert a.components["kv_transfer"] == pytest.approx(0.1, abs=1e-6)
    # Carve-outs move time within the component, preserving the total.
    assert a.components["compile_stall"] == pytest.approx(0.3, abs=1e-6)
    assert a.components["swap_stall"] == pytest.approx(0.2, abs=1e-6)
    assert a.components["prefill_compute"] == pytest.approx(0.6, abs=1e-6)
    assert a.components["decode_compute"] == pytest.approx(1.3, abs=1e-6)
    assert a.components["queue_wait"] == pytest.approx(0.2, abs=1e-6)
    # Unclaimed edge overhead books as `other`, never disappears.
    assert a.components["other"] == pytest.approx(0.3, abs=1e-6)
    assert a.dominant == "decode_compute"
    assert a.prompt_tokens == 256 and a.generated_tokens == 32
    # chip-seconds = compute components; page-seconds = pages * compute.
    compute = sum(
        a.components[c]
        for c in ("prefill_compute", "compile_stall", "decode_compute",
                  "host_gap")
    )
    assert a.chip_seconds == pytest.approx(compute, abs=1e-6)
    assert a.kv_page_seconds == pytest.approx(6 * compute, abs=1e-5)


def test_decomposition_deterministic_across_span_order():
    spans = _synthetic_trace()
    base = anatomy_from_spans(spans).to_dict()
    for seed in (1, 2, 3):
        shuffled = list(spans)
        random.Random(seed).shuffle(shuffled)
        assert anatomy_from_spans(shuffled).to_dict() == base


def test_preemption_claims_instants_from_decode():
    t = "p" * 32
    spans = [
        _span("http_request", t, "r", 0.0, 4.0, request_id="req-p"),
        _span("decode", t, "d", 0.5, 4.0, parent="r", generated_tokens=8),
        _span("preemption", t, "e", 1.0, 2.5, parent="r"),
    ]
    a = anatomy_from_spans(spans)
    assert a.components["preemption"] == pytest.approx(1.5, abs=1e-6)
    assert a.components["decode_compute"] == pytest.approx(2.0, abs=1e-6)
    assert a.total_s == pytest.approx(a.edge_latency_s, abs=1e-6)


def test_anatomy_from_timing_invariant_and_clamps():
    a = anatomy_from_timing(
        "req-t", queue_s=0.2, prefill_s=0.5, decode_s=1.0,
        compile_s=0.7, swap_s=0.4, preempt_s=0.3, gap_frac=0.1,
        edge_latency_s=2.5, prompt_tokens=64, generated_tokens=16,
        priority=2, page_seconds=8.0,
    )
    # compile clamps into prefill, swap into decode, gap out of decode.
    assert a.components["compile_stall"] == pytest.approx(0.5)
    assert a.components["prefill_compute"] == pytest.approx(0.0)
    assert a.components["swap_stall"] == pytest.approx(0.4)
    assert a.components["host_gap"] == pytest.approx(0.06)
    assert a.components["decode_compute"] == pytest.approx(0.54)
    assert a.total_s == pytest.approx(2.5, abs=1e-6)
    assert a.components["other"] == pytest.approx(0.5, abs=1e-6)
    # Round-trip through the mirror dict (`llmctl slow` live path).
    back = RequestAnatomy.from_dict(a.to_dict())
    assert back.components == a.to_dict()["components"]
    assert back.dominant == a.dominant


def test_anatomy_from_flight_state_machine():
    block = {
        "events": [
            {"seq": 0, "t": 10.0, "kind": "admit", "req": "r1", "slot": 0,
             "prompt": 32, "cached": 0, "priority": 1},
            {"seq": 1, "t": 10.5, "kind": "first_token", "req": "r1"},
            {"seq": 2, "t": 11.0, "kind": "preempt", "req": "r1"},
            {"seq": 3, "t": 12.0, "kind": "admit", "req": "r1", "slot": 1},
            {"seq": 4, "t": 12.2, "kind": "first_token", "req": "r1"},
            {"seq": 5, "t": 12.4, "kind": "stall_start", "req": "r1"},
            {"seq": 6, "t": 12.6, "kind": "stall_end", "req": "r1"},
            {"seq": 7, "t": 13.0, "kind": "finish", "req": "r1",
             "generated": 12, "pages": 3, "priority": 1},
            # A request whose admit fell off the ring: skipped, not
            # invented.
            {"seq": 8, "t": 13.5, "kind": "finish", "req": "r2"},
        ]
    }
    out = anatomy_from_flight(block)
    assert len(out) == 1
    a = out[0]
    assert a.request_id == "r1"
    assert a.components["prefill_compute"] == pytest.approx(0.7, abs=1e-6)
    assert a.components["preemption"] == pytest.approx(1.0, abs=1e-6)
    assert a.components["swap_stall"] == pytest.approx(0.2, abs=1e-6)
    assert a.components["decode_compute"] == pytest.approx(1.1, abs=1e-6)
    assert a.total_s == pytest.approx(a.edge_latency_s, abs=1e-6)
    assert anatomy_from_flight(block, "r2") == []


def test_anatomy_ring_bounded_worst_first():
    ring = AnatomyRing(capacity=3)
    for i in range(8):
        ring.offer(
            anatomy_from_timing(
                f"req-{i}", queue_s=0.0, prefill_s=0.1, decode_s=float(i),
                compile_s=0.0, swap_s=0.0, preempt_s=0.0, gap_frac=0.0,
                edge_latency_s=0.1 + i,
            )
        )
    snap = ring.snapshot()
    assert [d["request_id"] for d in snap] == ["req-7", "req-6", "req-5"]
    assert all(set(d["components"]) == set(COMPONENTS) for d in snap)


# --------------------------------------------------------------- fixture
def test_fixture_traces_decompose_and_render():
    spans = load_spans([FIXTURE])
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    assert len(by_trace) == 3
    for group in by_trace.values():
        a = anatomy_from_spans(group)
        assert a.total_s == pytest.approx(a.edge_latency_s, abs=1e-5)
        rendered = render_anatomy(a)
        assert "dominant" in rendered and "chip-seconds" in rendered
    listing = render_slow(
        [anatomy_from_spans(g) for g in by_trace.values()], n=3
    )
    assert "req-fixture-2" in listing  # worst edge latency first
    assert listing.index("req-fixture-2") < listing.index("req-fixture-1")


def test_fixture_fingerprint_digest_pinned():
    fp = fingerprint_from_spans(load_spans([FIXTURE]))
    assert fp.n == 3
    assert fp.digest()[:32] == FIXTURE_DIGEST
    assert fp.priority_mix == (
        pytest.approx(1 / 3, abs=1e-3),
    ) * 3


# ----------------------------------------------------------- fingerprint
def test_fingerprint_digest_stable_across_feed_orders():
    def build(order, arrival_scale):
        b = FingerprintBuilder()
        for i in order:
            b.observe_admit(
                prompt_tokens=32 * (i + 1), cached_tokens=8 * i,
                priority=i % 3, arrival_t=1000.0 + i * arrival_scale,
            )
        for i in order:
            b.observe_finish(generated_tokens=16 * (i + 1))
        return b.snapshot()

    base = build(list(range(6)), 1.0)
    reordered = build([3, 0, 5, 2, 4, 1], 1.0)
    assert reordered.digest() == base.digest()
    # Wall-clock-derived fields ride alongside but never enter the
    # digest: stretching arrivals 50x changes the rate, not the hash.
    stretched = build(list(range(6)), 50.0)
    assert stretched.digest() == base.digest()
    assert stretched.arrival_rate_rps != base.arrival_rate_rps
    # Round-trip through the saved-reference format.
    from dynamo_exp_tpu.telemetry import WorkloadFingerprint

    back = WorkloadFingerprint.from_dict(base.to_dict())
    assert back.digest() == base.digest()


def test_fingerprint_replay_roundtrip():
    """fingerprint -> replay_workload -> re-fingerprint: the replayed
    population drifts < 0.2 from the source (PR-6-style calibration
    tolerance; the shape axes must essentially match) and is
    deterministic in the seed."""
    b = FingerprintBuilder()
    rng = random.Random(11)
    for i in range(300):
        isl = rng.choice((64, 128, 512, 900))
        b.observe_admit(isl, cached_tokens=isl // 4 if i % 2 else 0,
                        priority=rng.choice((1, 1, 1, 2, 0)),
                        arrival_t=500.0 + i * 0.25)
        b.observe_finish(rng.choice((16, 32, 128)))
    fp = b.snapshot()

    reqs = replay_workload(fp, seed=3, n=400)
    assert len(reqs) == 400
    assert reqs == replay_workload(fp, seed=3, n=400)  # seed-determinism
    assert reqs != replay_workload(fp, seed=4, n=400)

    rb = FingerprintBuilder()
    for r in reqs:
        rb.observe_admit(r.prompt_len, r.prefix_len if r.prefix_group >= 0
                         else 0, r.priority, r.arrival_s or 1e-9)
        rb.observe_finish(r.max_tokens)
    replayed = rb.snapshot()
    assert drift_score(replayed, fp) < 0.2
    # And identical populations score (near) zero drift.
    assert drift_score(fp, fp) == 0.0


def test_replay_drives_cluster_sim_with_anatomy():
    """The fingerprint→sim seam end to end: a replayed workload runs
    through ClusterSim, the report carries the anatomy rollup, and the
    whole thing is bit-deterministic per seed."""
    from dynamo_exp_tpu.sim import ClusterSim, SimConfig

    b = FingerprintBuilder()
    for i in range(24):
        b.observe_admit(24 + 8 * (i % 3), priority=1,
                        arrival_t=100.0 + i * 0.05)
        b.observe_finish(6 + (i % 4))
    reqs = replay_workload(b.snapshot(), seed=5, n=12, rate_rps=50.0)

    def run():
        cfg = SimConfig(seed=0, slots_per_instance=4, pages_per_instance=64,
                        page_size=8, initial_instances=1)
        return ClusterSim(cfg, reqs).run()

    r1, r2 = run(), run()
    assert r1.completed > 0
    assert set(r1.anatomy) == {
        "queue_wait", "prefill_compute", "decode_compute", "preemption"
    }
    assert r1.anatomy["prefill_compute"] > 0
    assert r1.anatomy["decode_compute"] > 0
    assert r1.to_dict() == r2.to_dict()
    assert "anatomy" in r1.to_dict()


# ------------------------------------------------------- burn rate, drift
def test_multi_window_burn_rates():
    from dynamo_exp_tpu.telemetry.slo import SloAttribution, SloConfig

    slo = SloAttribution(SloConfig(ttft_s=0.5, itl_s=0.05))
    for _ in range(8):
        slo.count(1, ttft_s=0.1, itl_s=0.01)  # all met
    rates = slo.burn_rates()
    assert rates["ttft/fast"] == 0.0 and rates["itl/slow"] == 0.0
    for _ in range(8):
        slo.count(1, ttft_s=2.0, itl_s=0.01)  # ttft breached
    rates = slo.burn_rates()
    assert rates["ttft/fast"] == pytest.approx(0.5)
    assert rates["ttft/slow"] == pytest.approx(0.5)
    assert rates["itl/fast"] == 0.0
    # An unmeasurable axis (1-token response) never dilutes the window.
    slo.count(1, ttft_s=2.0, itl_s=None)
    assert sum(len(w) for (s, _), w in slo._burn.items() if s == "itl") == 32
    # The fast window forgets; the slow window remembers (fast = 64
    # requests, so 64 clean ones wash the breaches out of fast only).
    for _ in range(64):
        slo.count(1, ttft_s=0.1, itl_s=0.01)
    rates = slo.burn_rates()
    assert rates["ttft/fast"] == 0.0
    assert rates["ttft/slow"] > 0.0


def test_burn_rate_gauge_exported():
    from prometheus_client import CollectorRegistry

    from dynamo_exp_tpu.telemetry.slo import SloAttribution, SloConfig
    from dynamo_exp_tpu.telemetry.spans import Telemetry

    hub = Telemetry(CollectorRegistry())
    slo = SloAttribution(SloConfig(ttft_s=0.5), telemetry=hub)
    slo.count(1, ttft_s=2.0)
    assert hub.slo_burn_rate.labels("ttft", "fast")._value.get() == 1.0


def test_drift_watch_min_n_and_scoring():
    ref_b = FingerprintBuilder()
    for i in range(32):
        ref_b.observe_admit(128, priority=1, arrival_t=10.0 + i)
        ref_b.observe_finish(32)
    ref = ref_b.snapshot()

    live = FingerprintBuilder()
    watch = WorkloadDriftWatch(live, ref, min_n=8)
    assert watch.score() == 0.0  # too few samples to accuse anyone
    for i in range(8):
        live.observe_admit(4096, priority=0, arrival_t=20.0 + i)
        live.observe_finish(512)
    s = watch.score()
    assert s > 0.3  # a genuinely different workload
    assert WorkloadDriftWatch(live, None).score() == 0.0


def test_fleet_rollup_and_top_carry_drift():
    from dynamo_exp_tpu.telemetry.fleet import FleetView, render_top

    view = FleetView.from_snapshots({
        "a": {"num_requests_running": 1, "workload_drift_score": 0.41},
        "b": {"num_requests_running": 0, "workload_drift_score": 0.05},
    })
    roll = view.rollup()
    assert roll["workload_drift"] == pytest.approx(0.41)  # max, not mean
    body = render_top(view)
    assert "DRIFT:0.41" in body
    assert "DRIFT:0.05" not in body  # below the flag threshold


# -------------------------------------------------------------- live engine
def make_engine(**env) -> TPUEngine:
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=64,
        max_model_len=128,
        eos_token_ids=[],
        kv_dtype="float32",
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def _drive(engine, n_requests=3, max_tokens=4, prompt_len=12):
    async def one(i):
        b = BackendInput(token_ids=list(range(3, 3 + prompt_len)))
        b.stop_conditions.max_tokens = max_tokens
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict())
        tokens = []
        async for item in stream:
            tokens.extend(item.get("token_ids", []))
        return tokens

    return await asyncio.gather(*[one(i) for i in range(n_requests)])


async def test_engine_anatomy_and_fingerprint_mirrors():
    """Live acceptance: finished requests land in the anatomy mirrors,
    every exemplar's component sum explains its edge latency exactly
    (the within-5% acceptance bound, met by construction engine-side),
    and the fingerprint digest is identical across two same-shape
    runs."""
    e1 = make_engine()
    try:
        outs = await _drive(e1, n_requests=3)
        assert all(len(t) == 4 for t in outs)
        m = e1.metrics()
        assert m["anatomy_requests"] == 3
        assert set(m["anatomy_totals"]) == set(COMPONENTS)
        slow = m["anatomy_slow"]
        assert len(slow) == 3
        for d in slow:
            total = sum(d["components"].values())
            assert d["edge_latency_s"] > 0
            # Acceptance: components explain the edge latency within 5%.
            assert total == pytest.approx(d["edge_latency_s"], rel=0.05)
            assert d["prompt_tokens"] == 12 and d["generated_tokens"] == 4
        # Totals are the sum over requests, and the prometheus family
        # mirrors them.
        from prometheus_client import REGISTRY as _  # noqa: F401
        from dynamo_exp_tpu.telemetry import get_telemetry

        fam = {
            tuple(s.labels.values()): s.value
            for metric in get_telemetry().registry.collect()
            if metric.name == "dynamo_request_seconds"
            for s in metric.samples
            if s.name.endswith("_total")
        }
        for comp, v in m["anatomy_totals"].items():
            if v > 0:
                assert fam.get((comp,), 0.0) >= v * 0.99
        assert m["workload_requests"] == 3
        digest1 = m["workload_fingerprint"]
        assert m["workload_drift_score"] == 0.0  # no reference pinned
    finally:
        e1.stop()

    e2 = make_engine()
    try:
        await _drive(e2, n_requests=3)
        assert e2.metrics()["workload_fingerprint"] == digest1
    finally:
        e2.stop()


async def test_engine_drift_watch_reads_reference(tmp_path, monkeypatch):
    """DYN_WORKLOAD_REF pins a reference at boot; a live mix far from
    it drives the drift mirror (and gauge) above zero."""
    ref_b = FingerprintBuilder()
    for i in range(16):
        ref_b.observe_admit(4096, priority=2, arrival_t=5.0 + i)
        ref_b.observe_finish(1024)
    ref_path = tmp_path / "ref.json"
    ref_path.write_text(json.dumps(ref_b.snapshot().to_dict()))
    monkeypatch.setenv("DYN_WORKLOAD_REF", str(ref_path))
    monkeypatch.setenv("DYN_ANATOMY_RING", "2")

    engine = make_engine()
    try:
        assert engine.drift_watch.reference is not None
        assert engine.drift_watch.min_n <= 8
        await _drive(engine, n_requests=8, max_tokens=2)
        m = engine.metrics()
        assert m["workload_drift_score"] > 0.3
        assert len(m["anatomy_slow"]) == 2  # DYN_ANATOMY_RING honored
    finally:
        engine.stop()


# ------------------------------------------------------------- llmctl CLI
def _run_cli(argv):
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = asyncio.run(llmctl.run(llmctl.build_parser().parse_args(argv)))
    return rc, out.getvalue()


def test_llmctl_trace_why_over_fixture():
    rc, out = _run_cli(
        ["trace", "aaaa1111", "--trace-file", FIXTURE, "--why"]
    )
    assert rc == 0
    assert "dominant: decode_compute" in out
    assert "compile_stall" in out and "kv_transfer" in out
    assert "chip-seconds" in out


def test_llmctl_slow_offline_over_fixture():
    rc, out = _run_cli(["slow", "--trace-file", FIXTURE, "-n", "2"])
    assert rc == 0
    assert "req-fixture-2" in out and "req-fixture-3" not in out
    rc, out = _run_cli(
        ["slow", "--trace-file", FIXTURE, "--by", "ttft", "--why"]
    )
    assert rc == 0
    assert "by ttft" in out and "dominant:" in out


def test_llmctl_fingerprint_json_ref_and_replay(tmp_path):
    ref = str(tmp_path / "ref.json")
    rc, out = _run_cli(["fingerprint", FIXTURE, "--json", "--out", ref])
    assert rc == 0
    doc = json.loads(out[out.index("{"):])
    assert doc["digest"][:32] == FIXTURE_DIGEST
    assert os.path.exists(ref)

    rc, out = _run_cli(["fingerprint", FIXTURE, "--ref", ref])
    assert rc == 0
    assert "drift" in out and "0.0000" in out  # self-drift is zero

    replay = str(tmp_path / "replay.jsonl")
    rc, _ = _run_cli(
        ["fingerprint", ref, "--replay-out", replay, "--requests", "50",
         "--seed", "3"]
    )
    assert rc == 0
    from dynamo_exp_tpu.sim import load_trace

    assert len(load_trace(replay)) == 50


def test_llmctl_flight_why(tmp_path):
    dump = tmp_path / "flight.jsonl"
    lines = [
        {"type": "flight_header", "reason": "test", "capacity": 16,
         "dumped_at": 0.0},
        {"type": "flight_event", "seq": 0, "t": 1.0, "kind": "admit",
         "req": "rq", "slot": 0, "prompt": 16, "cached": 0, "priority": 1},
        {"type": "flight_event", "seq": 1, "t": 1.4, "kind": "first_token",
         "req": "rq"},
        {"type": "flight_event", "seq": 2, "t": 2.0, "kind": "finish",
         "req": "rq", "generated": 6, "pages": 2, "priority": 1},
    ]
    dump.write_text("\n".join(json.dumps(x) for x in lines) + "\n")
    rc, out = _run_cli(["flight", str(dump), "--why"])
    assert rc == 0
    assert "request rq" in out and "dominant: decode_compute" in out
    rc, out = _run_cli(["flight", str(dump), "--why", "--req", "nope"])
    assert rc != 0 or "no request anatomy" in out


def test_llmctl_top_json_over_fake_runtime(capsys):
    class _Addr:
        component = "TpuWorker"

    class _Info:
        def __init__(self, iid):
            self.address = _Addr()
            self.instance_id = iid
            self.metadata = {}

    class _Discovery:
        async def list_instances(self, _prefix):
            return [_Info(1)]

    class _Plane:
        async def scrape_stats(self, info):
            return {
                "num_requests_running": 2,
                "workload_drift_score": 0.31,
            }

    class _Drt:
        discovery = _Discovery()
        request_plane = _Plane()

    class _Args:
        once = False
        interval = 2.0
        json = True

    rc = asyncio.run(llmctl.run_top(_Drt(), _Args()))
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["rollup"]["workload_drift"] == pytest.approx(0.31)
    assert doc["instances"]["TpuWorker/1"]["workload_drift"] == (
        pytest.approx(0.31)
    )
    assert doc["missing"] == {}


def test_bench_compare_judges_anatomy_fields():
    from dynamo_exp_tpu.telemetry.bench_compare import compare_bench

    old = [{"metric": "m", "unit": "tok/s", "value": 100.0,
            "anatomy": {"decode_compute": 1.0, "queue_wait": 0.1}}]
    new = [{"metric": "m", "unit": "tok/s", "value": 100.0,
            "anatomy": {"decode_compute": 1.5, "queue_wait": 0.1}}]
    rep = compare_bench(old, new)
    assert [f.field for f in rep.regressions] == ["anatomy.decode_compute"]
    # Improvements report too; absent/zero components never divide.
    rep2 = compare_bench(new, old)
    assert [f.field for f in rep2.findings] == ["anatomy.decode_compute"]
    assert rep2.findings[0].kind == "improvement"
