"""Standalone metrics exporter: worker load + KV hit rate → Prometheus.

Capability parity with ``/root/reference/components/metrics/``
(``src/lib.rs:80-167`` ``PrometheusMetricsCollector``): scrape a target
component's ``ForwardPassMetrics`` from the stats plane, subscribe to
``kv-hit-rate`` events, expose everything on ``/metrics`` for a
Prometheus pull. Run standalone:

    python -m dynamo_exp_tpu.components.metrics \
        --coordinator HOST:PORT --component ns.comp [--port 9091]
"""

from __future__ import annotations

import asyncio
import contextlib
import logging

from aiohttp import web
from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    generate_latest,
)

from ..http.metrics import CONTENT_TYPE_LATEST
from ..kv_router.metrics_aggregator import KvMetricsAggregator
from ..telemetry import get_telemetry
from ..kv_router.protocols import KV_HIT_RATE_SUBJECT
from ..runtime.component import Component

logger = logging.getLogger(__name__)

_GAUGES = (
    ("request_active_slots", "Active request slots"),
    ("request_total_slots", "Total request slots"),
    ("kv_active_blocks", "Active KV blocks"),
    ("kv_total_blocks", "Total KV blocks"),
    ("num_requests_waiting", "Requests waiting for admission"),
    ("gpu_cache_usage_perc", "Device KV cache usage fraction"),
    ("gpu_prefix_cache_hit_rate", "Device prefix-cache hit rate"),
)


class MetricsService:
    """Scrapes one component and serves /metrics."""

    def __init__(
        self,
        component: Component,
        host: str = "0.0.0.0",
        port: int = 9091,
        scrape_interval_s: float = 1.0,
    ):
        self.component = component
        self.host = host
        self.port = port
        self.registry = CollectorRegistry()
        self.gauges = {
            name: Gauge(
                f"llm_kv_{name}", help_, ["worker_id"], registry=self.registry
            )
            for name, help_ in _GAUGES
        }
        self.hit_events = Counter(
            "llm_kv_hit_events_total",
            "KV-aware routing decisions observed",
            registry=self.registry,
        )
        self.hit_isl_blocks = Counter(
            "llm_kv_hit_isl_blocks_total",
            "Input blocks across routing decisions",
            registry=self.registry,
        )
        self.hit_overlap_blocks = Counter(
            "llm_kv_hit_overlap_blocks_total",
            "Matched (cache-hit) blocks across routing decisions",
            registry=self.registry,
        )
        self.aggregator = KvMetricsAggregator(component, scrape_interval_s)
        self._hit_task: asyncio.Task | None = None
        self._export_task: asyncio.Task | None = None
        self._runner: web.AppRunner | None = None

    async def start(self) -> int:
        await self.aggregator.start()
        # Subscribe before returning so events published right after
        # start() are counted.
        stream = await self.component.drt.event_plane.subscribe(
            KV_HIT_RATE_SUBJECT
        )

        async def pump_hits(stream):
            # Re-subscribe on connection loss: a dead event stream must
            # not silently freeze the hit-rate counters forever. A dead
            # generator is never re-iterated: each drain failure
            # discards the stream and retries the subscribe until it
            # succeeds.
            while True:
                try:
                    async for event in stream:
                        self.hit_events.inc()
                        self.hit_isl_blocks.inc(max(event.get("isl_blocks", 0), 0))
                        self.hit_overlap_blocks.inc(
                            max(event.get("overlap_blocks", 0), 0)
                        )
                    return
                except asyncio.CancelledError:
                    return
                except Exception as exc:
                    logger.warning("hit-event stream lost (%s); retrying", exc)
                stream = None
                while stream is None:
                    await asyncio.sleep(1.0)
                    try:
                        stream = await self.component.drt.event_plane.subscribe(
                            KV_HIT_RATE_SUBJECT
                        )
                    except asyncio.CancelledError:
                        return
                    except Exception:
                        pass

        async def pump_gauges():
            exported: set[str] = set()  # worker_ids with live series
            while True:
                await self.aggregator.updated.wait()
                self.aggregator.updated.clear()
                seen = set()
                for wid, m in self.aggregator.endpoints.metrics.items():
                    seen.add(str(wid))
                    for name, _ in _GAUGES:
                        self.gauges[name].labels(worker_id=str(wid)).set(
                            getattr(m, name)
                        )
                # Drop series for departed workers so dashboards don't
                # show ghosts (reference clears on scrape too). Track our
                # own exported set rather than walking prometheus_client
                # internals.
                for wid in exported - seen:
                    for name, _ in _GAUGES:
                        with contextlib.suppress(KeyError):
                            self.gauges[name].remove(wid)
                exported = seen

        self._hit_task = asyncio.ensure_future(pump_hits(stream))
        self._export_task = asyncio.ensure_future(pump_gauges())

        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            srv = getattr(s, "_server", None)
            if srv and srv.sockets:
                self.port = srv.sockets[0].getsockname()[1]
        logger.info("metrics exporter on %s:%d", self.host, self.port)
        return self.port

    async def _metrics(self, request: web.Request) -> web.Response:
        # CONTENT_TYPE_LATEST is e.g. "text/plain; version=0.0.4;
        # charset=utf-8" — aiohttp wants content_type and charset split.
        ctype, _, _ = CONTENT_TYPE_LATEST.partition(";")
        return web.Response(
            body=self.render(),
            content_type=ctype.strip(),
            charset="utf-8",
        )

    def render(self) -> bytes:
        # Unified scrape: aggregator gauges + the process-wide telemetry
        # registry (stage histograms, engine gauges, transfer metrics).
        return generate_latest(self.registry) + get_telemetry().render()

    async def stop(self) -> None:
        for t in (self._hit_task, self._export_task):
            if t is not None:
                t.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await t
        self._hit_task = self._export_task = None
        await self.aggregator.stop()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


def main() -> None:  # pragma: no cover - CLI entry
    import argparse

    from ..runtime.component import DistributedRuntime
    from ..runtime.config import RuntimeConfig

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator", required=True)
    p.add_argument("--component", required=True, help="namespace.component")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9091)
    p.add_argument("--scrape-interval", type=float, default=1.0)
    args = p.parse_args()

    async def run():
        cfg = RuntimeConfig(coordinator_endpoint=args.coordinator)
        drt = DistributedRuntime(config=cfg)
        ns, _, comp = args.component.partition(".")
        svc = MetricsService(
            drt.namespace(ns).component(comp),
            args.host,
            args.port,
            args.scrape_interval,
        )
        port = await svc.start()
        print(f"metrics on http://{args.host}:{port}/metrics", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
