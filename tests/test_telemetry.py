"""End-to-end request tracing + per-stage telemetry.

Covers the observability acceptance path: one request through an
in-proc disagg graph (HTTP ingress → preprocess → disagg decode →
prefill worker → KV transfer → decode) yields a single connected trace
with ≥5 stage spans, the stage histograms surface on ``/metrics``,
JSONL log lines carry the trace_id, and ``llmctl trace`` reconstructs
the timeline from the recorder JSONL.
"""

import asyncio
import io
import json
import logging

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from dynamo_exp_tpu import llmctl
from dynamo_exp_tpu.disagg import (
    DisaggConfig,
    DisaggConfigWatcher,
    DisaggDecodeEngine,
    KvPageReceiver,
    PrefillWorker,
)
from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.runtime.logging import JsonlFormatter
from dynamo_exp_tpu.runtime.runtime import CancellationToken
from dynamo_exp_tpu.runtime.transports.inproc import (
    InProcDiscovery,
    InProcWorkQueue,
)
from dynamo_exp_tpu.telemetry import (
    Span,
    current_trace,
    find_trace,
    get_telemetry,
    load_spans,
    new_trace,
    render_timeline,
    span,
)

PS = 8


def make_engine() -> TPUEngine:
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=64,
        max_model_len=128,
        eos_token_ids=[],
        kv_dtype="float32",
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


# ----------------------------------------------------------------- unit level
def test_span_nesting_and_contextvar():
    assert current_trace() is None
    with span("outer") as outer:
        assert current_trace() is outer.context
        with span("inner") as inner:
            assert inner.context.trace_id == outer.context.trace_id
            assert inner._parent_id == outer.context.span_id
    assert current_trace() is None


def test_span_records_to_recorder(tmp_path):
    tel = get_telemetry()
    path = str(tmp_path / "trace.jsonl")
    tel.configure(path)
    try:
        with span("solo", foo=1):
            pass
        spans = load_spans([path])
        assert [s.stage for s in spans] == ["solo"]
        # Every span carries the process's instance identity (the fleet
        # plane's multi-instance stitching, docs/observability.md).
        assert spans[0].attrs == {"foo": 1, "instance": tel.instance}
        assert spans[0].duration_s >= 0
    finally:
        tel.configure(None)


def test_emit_stage_without_trace_is_dropped(tmp_path):
    tel = get_telemetry()
    path = str(tmp_path / "trace.jsonl")
    tel.configure(path)
    try:
        tel.emit_stage("ghost", 0.0, 1.0, None)
        assert load_spans([path]) == []
        tc = new_trace()
        tel.emit_stage("real", 0.0, 1.0, tc, n=3)
        (s,) = load_spans([path])
        assert s.trace_id == tc.trace_id and s.parent_span_id == tc.span_id
    finally:
        tel.configure(None)


def test_configure_from_env_records_per_process(tmp_path, monkeypatch):
    """DYN_TRACE_FILE is shared by a whole graph's processes; each one
    must record to its own <path>.<pid> (single-writer rotation), and
    load_spans must find the siblings through the base path."""
    import os

    base = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("DYN_TRACE_FILE", base)
    tel = get_telemetry()
    tel.configure_from_env()
    try:
        assert tel.trace_file == f"{base}.pid{os.getpid()}"
        with span("from-env"):
            pass
    finally:
        tel.configure(None)
    assert [s.stage for s in load_spans([base])] == ["from-env"]


def test_recorder_rotation_env_and_atexit_flush(tmp_path, monkeypatch):
    """Satellite: the span recorder is bounded — DYN_TRACE_ROTATE_MB /
    DYN_TRACE_KEEP size the rotation, and the atexit flush hook closes
    the live file so a dying worker doesn't lose its tail."""
    monkeypatch.setenv("DYN_TRACE_ROTATE_MB", "0.001")  # ~1 KiB
    monkeypatch.setenv("DYN_TRACE_KEEP", "2")
    tel = get_telemetry()
    path = str(tmp_path / "t.jsonl")
    tel.configure(path)
    try:
        assert tel._recorder.max_bytes == int(0.001 * (1 << 20))
        assert tel._recorder.max_files == 2
        for i in range(40):  # ~150 bytes/span: forces rotation
            with span("rot", i=i):
                pass
        import os

        assert os.path.exists(path + ".1"), "no rotation happened"
        assert not os.path.exists(path + ".3")  # keep-N bound
        # Bounded retention: the newest spans survive across the kept
        # generations; older generations were deleted (the point of the
        # bound), never grown forever.
        spans = load_spans([path])
        assert 0 < len(spans) < 40
        assert max(s.attrs["i"] for s in spans) == 39  # newest kept
        # Crash-flush path: the atexit hook closes the live recorder
        # (idempotent; a normal configure(None) later is a no-op).
        assert tel._atexit_registered
        tel._flush_at_exit()
        assert tel._recorder is None
    finally:
        tel.configure(None)


def test_invalid_rotation_env_falls_back(tmp_path, monkeypatch):
    monkeypatch.setenv("DYN_TRACE_ROTATE_MB", "not-a-number")
    tel = get_telemetry()
    tel.configure(str(tmp_path / "t.jsonl"))
    try:
        assert tel._recorder.max_bytes == 64 << 20  # default
    finally:
        tel.configure(None)


def test_load_spans_follows_rotations_and_skips_non_numeric(tmp_path):
    tel = get_telemetry()
    path = str(tmp_path / "t.jsonl")
    tel.configure(path)
    try:
        with span("newer"):
            pass
    finally:
        tel.configure(None)
    # A rotated generation plus glob-matching junk siblings.
    import shutil

    with open(path + ".1", "w") as f:
        older = Span("older", "tid", "sid", "", 1.0, 2.0)
        f.write(json.dumps({"ts": 2.0, "event": older.to_event()}) + "\n")
    shutil.copy(path + ".1", path + ".1.bak")  # must not crash load_spans
    stages = [s.stage for s in load_spans([path])]
    assert stages == ["older", "newer"]  # rotation read first (oldest)


def test_timeline_find_by_request_id_and_render():
    tc = new_trace()
    spans = [
        Span("http_request", tc.trace_id, "a", "", 0.0, 1.0,
             {"request_id": "req-9"}),
        Span("preprocess", tc.trace_id, "b", "a", 0.1, 0.2),
        Span("decode", tc.trace_id, "c", "a", 0.3, 0.9, {"generated_tokens": 4}),
    ]
    got = find_trace(spans, "req-9")
    assert len(got) == 3
    out = render_timeline(got)
    assert "http_request" in out and "preprocess" in out
    assert "req-9" in out
    # children indented under the root
    assert "\n  preprocess" in out


# --------------------------------------------------------- e2e disagg trace
async def test_disagg_request_produces_connected_trace(tmp_path, tiny_model_dir):
    """Acceptance: one HTTP request through the in-proc disagg graph →
    one trace, ≥5 stage spans sharing a trace_id, stage histograms on
    /metrics, trace_id in JSONL log lines emitted during handling."""
    from dynamo_exp_tpu.http import HttpService, build_pipeline_engine
    from dynamo_exp_tpu.model_card import ModelDeploymentCard

    from dynamo_exp_tpu.telemetry import get_transfer_ledger

    tel = get_telemetry()
    trace_file = str(tmp_path / "trace.jsonl")
    tel.configure(trace_file)
    get_transfer_ledger().reset()

    prefill_eng, decode_eng = make_engine(), make_engine()
    queue = InProcWorkQueue()
    recv = KvPageReceiver()
    await recv.start()
    cancel = CancellationToken()
    worker = PrefillWorker(prefill_eng, queue, cancel)
    worker_task = asyncio.ensure_future(worker.run())
    watcher = DisaggConfigWatcher(
        InProcDiscovery(), "tiny",
        default=DisaggConfig(max_local_prefill_length=0),  # force remote
    )
    disagg = DisaggDecodeEngine(decode_eng, queue, recv, watcher)

    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir, "tiny")
    mdc.kv_cache_block_size = PS
    svc = HttpService()
    svc.manager.add_chat_model("tiny", build_pipeline_engine(mdc, disagg))

    # Capture JSONL log lines emitted while the request is handled.
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    handler.setFormatter(JsonlFormatter())
    root_logger = logging.getLogger()
    root_logger.addHandler(handler)
    old_level = root_logger.level
    root_logger.setLevel(logging.INFO)

    client = TestClient(TestServer(svc.app))
    await client.start_server()
    try:
        body = {
            "model": "tiny",
            "messages": [{"role": "user", "content": "hello " * 30}],
            "max_tokens": 5,
            "stream": False,
        }
        r = await client.post("/v1/chat/completions", json=body)
        assert r.status == 200, await r.text()
        assert disagg.remote_prefills == 1

        m = await client.get("/metrics")
        metrics_text = await m.text()

        # Idle decay: the engine loop publishes gauges on its idle path
        # too, so "requests running" clears after the last request
        # instead of freezing on the final busy-loop snapshot.
        await asyncio.sleep(0.8)
        assert (
            get_telemetry().engine_gauges["num_requests_running"]._value.get()
            == 0
        )
    finally:
        root_logger.removeHandler(handler)
        root_logger.setLevel(old_level)
        await client.close()
        cancel.cancel()
        await asyncio.wait_for(worker_task, 5)
        await recv.close()
        for e in (prefill_eng, decode_eng):
            e.stop()
        tel.configure(None)

    spans = load_spans([trace_file])
    assert spans, "no spans recorded"
    trace_ids = {s.trace_id for s in spans}
    assert len(trace_ids) == 1, f"trace fragmented: {trace_ids}"
    stages = {s.stage for s in spans}
    # HTTP ingress → preprocess → remote prefill hand-off → prefill
    # worker compute → KV transfer both directions → decode.
    expected = {
        "http_request", "preprocess", "remote_prefill", "queue_wait",
        "prefill", "kv_transfer_send", "kv_transfer_recv", "decode",
        # The handoff lease's grant -> confirm hop (fleet plane,
        # docs/observability.md "Fleet plane").
        "kv_lease",
    }
    assert expected <= stages
    assert len(spans) >= 5

    # Fleet-plane acceptance: the trace's transfer hops carry the link
    # endpoints, and the TransferLedger's per-link bandwidth estimate is
    # consistent with the traced extract->ack durations.
    from dynamo_exp_tpu.telemetry import transfer_hops

    hops = transfer_hops(spans)
    assert hops, "no transfer hops in the stitched trace"
    for hop in hops:
        assert hop["src"] == tel.instance  # in-proc graph: one identity
        assert hop["bytes"] > 0 and hop["duration_s"] > 0
    lease_spans = [s for s in spans if s.stage == "kv_lease"]
    assert lease_spans and lease_spans[0].attrs["outcome"] == "confirmed"
    led = get_transfer_ledger()
    rates = [h["bytes"] / h["duration_s"] for h in hops]
    for hop in hops:
        bw = led.bandwidth_bps(hop["src"], hop["dst"])
        assert bw is not None
        # EWMA over the traced observations stays inside their range.
        assert min(rates) * 0.5 <= bw <= max(rates) * 2.0

    # Every non-root span parents into the tree (single connected trace).
    ids = {s.span_id for s in spans}
    roots = [s for s in spans if not s.parent_span_id]
    assert len(roots) == 1 and roots[0].stage == "http_request"
    for s in spans:
        if s.parent_span_id:
            assert s.parent_span_id in ids

    # Stage histograms + engine gauges surface on /metrics.
    for name in (
        "dynamo_stage_duration_seconds",
        "dynamo_engine_queue_wait_seconds",
        "dynamo_engine_prefill_seconds",
        "dynamo_engine_time_between_tokens_seconds",
        "dynamo_kv_transfer_bytes",
        "dynamo_engine_hbm_page_occupancy",
    ):
        assert name in metrics_text, name
    assert 'stage="prefill"' in metrics_text

    # Log correlation: JSONL lines during handling carry the trace_id.
    trace_id = next(iter(trace_ids))
    logged = [
        json.loads(line)
        for line in buf.getvalue().splitlines()
        if line.startswith("{")
    ]
    assert any(e.get("trace_id") == trace_id for e in logged)

    # llmctl trace reconstructs the timeline from the recorder output.
    import contextlib as _ctx

    out = io.StringIO()
    with _ctx.redirect_stdout(out):
        rc = await llmctl.run(
            llmctl.build_parser().parse_args(
                ["trace", trace_id[:8], "--trace-file", trace_file]
            )
        )
    assert rc == 0
    rendered = out.getvalue()
    assert "http_request" in rendered
    assert "kv_transfer_send" in rendered
    assert f"{len(spans)} spans" in rendered

    # ...and lists traces when called without an id.
    out = io.StringIO()
    with _ctx.redirect_stdout(out):
        rc = await llmctl.run(
            llmctl.build_parser().parse_args(
                ["trace", "--trace-file", trace_file]
            )
        )
    assert rc == 0
    assert trace_id in out.getvalue()


async def test_trace_rides_tcp_request_plane():
    """The request plane carries the caller's trace context: spans
    emitted inside the remote handler join the caller's trace."""
    from dynamo_exp_tpu.runtime.transports.base import (
        EndpointAddress,
        InstanceInfo,
    )
    from dynamo_exp_tpu.runtime.transports.tcp import TcpRequestPlane

    plane = TcpRequestPlane()
    seen: list = []

    async def handler(request, context):
        seen.append(current_trace())
        yield {"ok": True}

    info = InstanceInfo(
        address=EndpointAddress("ns", "comp", "ep"), instance_id=7
    )
    served = await plane.serve(info, handler)
    try:
        from dynamo_exp_tpu.runtime.engine import AsyncEngineContext

        with span("caller") as sp:
            stream = await plane.request_stream(
                info, {"x": 1}, AsyncEngineContext()
            )
            frames = [f async for f in stream]
        assert frames == [{"ok": True}]
        assert seen[0] is not None
        assert seen[0].trace_id == sp.context.trace_id
        assert seen[0].span_id == sp.context.span_id  # parents onto caller
    finally:
        await served.close()
        await plane.close()


# ------------------------------------------------------------- satellite fixes
async def test_coordinator_call_cancel_does_not_leak_pending():
    """A caller cancelled while awaiting the reply must not leave its
    entry in CoordinatorClient._pending forever."""
    from dynamo_exp_tpu.runtime.transports.coordinator import (
        CoordinatorClient,
        CoordinatorServer,
    )

    server = CoordinatorServer()
    await server.start()
    client = CoordinatorClient(server.address)
    await client.connect()
    try:
        # queue_pull with nothing queued blocks server-side: cancel the
        # caller mid-await.
        task = asyncio.ensure_future(
            client.call("queue_pull", {"queue": "q", "timeout_s": 30})
        )
        await asyncio.sleep(0.1)
        assert len(client._pending) == 1
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert len(client._pending) == 0
        # The connection is still usable for the next caller.
        h, _ = await client.call("queue_size", {"queue": "q"})
        assert h["size"] == 0
    finally:
        await client.close()
        await server.close()


async def test_card_sweep_rechecks_expiry_before_delete(monkeypatch):
    """A heartbeat landing mid-sweep must not lose its fresh card."""
    from dynamo_exp_tpu.http.discovery import ModelWatcher
    from dynamo_exp_tpu.local_model import MDC_BUCKET
    from dynamo_exp_tpu.model_card import ModelDeploymentCard
    from dynamo_exp_tpu.runtime.transports.inproc import InProcObjectStore

    store = InProcObjectStore()
    card = ModelDeploymentCard(display_name="m", model_path="/m")
    card.last_published = 0.0  # long expired
    await store.put(MDC_BUCKET, "m", card.to_json().encode())

    class _Drt:
        object_store = store

    fresh = ModelDeploymentCard(display_name="m", model_path="/m")
    fresh.stamp()  # heartbeat: freshly published

    orig_get = store.get
    calls = {"n": 0}

    async def racy_get(bucket, key):
        calls["n"] += 1
        if calls["n"] == 2:
            # Heartbeat wins the race between first read and delete.
            await store.put(MDC_BUCKET, "m", fresh.to_json().encode())
        return await orig_get(bucket, key)

    monkeypatch.setattr(store, "get", racy_get)
    watcher = ModelWatcher.__new__(ModelWatcher)
    watcher.drt = _Drt()

    async def run_once():
        task = asyncio.ensure_future(
            watcher._sweep_expired_cards(period_s=0.01)
        )
        await asyncio.sleep(0.2)
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task

    await run_once()
    raw = await orig_get(MDC_BUCKET, "m")
    assert raw is not None, "sweep deleted a freshly heartbeated card"
    assert not ModelDeploymentCard.from_json(raw.decode()).is_expired()


async def test_card_sweep_still_removes_stale_cards():
    from dynamo_exp_tpu.http.discovery import ModelWatcher
    from dynamo_exp_tpu.local_model import MDC_BUCKET
    from dynamo_exp_tpu.model_card import ModelDeploymentCard
    from dynamo_exp_tpu.runtime.transports.inproc import InProcObjectStore

    store = InProcObjectStore()
    card = ModelDeploymentCard(display_name="m", model_path="/m")
    card.last_published = 0.0
    await store.put(MDC_BUCKET, "stale", card.to_json().encode())

    class _Drt:
        object_store = store

    watcher = ModelWatcher.__new__(ModelWatcher)
    watcher.drt = _Drt()
    task = asyncio.ensure_future(watcher._sweep_expired_cards(period_s=0.01))
    for _ in range(100):
        if await store.get(MDC_BUCKET, "stale") is None:
            break
        await asyncio.sleep(0.01)
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    assert await store.get(MDC_BUCKET, "stale") is None


# ---------------------------------------------------- metric doc-sync guard
def _observability_doc() -> str:
    import os

    doc_path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "observability.md"
    )
    with open(doc_path) as f:
        return f.read()


def test_every_registered_metric_name_is_documented():
    """Doc-sync guard: every ``dynamo_*`` metric registered by the
    telemetry hub — counters, gauges, AND histograms — must appear in
    docs/observability.md; new series land with their documentation or
    not at all (this is exactly the drift a PR adding counters would
    otherwise start)."""
    from prometheus_client import CollectorRegistry

    from dynamo_exp_tpu.telemetry.spans import Telemetry

    doc = _observability_doc()
    hub = Telemetry(CollectorRegistry())
    missing = []
    seen_types = set()
    for family in hub.registry.collect():
        seen_types.add(family.type)
        # The client lib reports counters by base name; the exposition
        # (and the docs) use the _total suffix.
        name = family.name + ("_total" if family.type == "counter" else "")
        if name.startswith("dynamo_") and name not in doc:
            missing.append(name)
    # The walk really does cover all three instrument kinds (a refactor
    # that silently dropped one family type would hollow the guard out).
    assert {"counter", "gauge", "histogram"} <= seen_types
    assert not missing, (
        f"metrics registered in telemetry/ but undocumented in "
        f"docs/observability.md: {sorted(missing)}"
    )


def test_every_engine_metrics_mirror_key_is_documented():
    """Doc-sync guard (PR 9 extension): every ``engine.metrics()``
    mirror key — including the host-tier keys and the per-kind
    dispatch-profiler stat fields — must appear in
    docs/observability.md, so the stats-plane surface bench.py and the
    sim fit consume can't drift undocumented."""
    from dynamo_exp_tpu.telemetry.dispatch import SUMMARY_FIELDS

    doc = _observability_doc()
    engine = make_engine()
    try:
        # Host tier on a throwaway copy of the config surface: the
        # host_cache_* keys only exist when the tier is enabled.
        m = dict(engine.metrics())
        m.update(
            {"host_cache_resident": 0, "host_cache_hits": 0,
             "host_cache_stores": 0}
        )
        missing = [k for k in m if f"`{k}`" not in doc]
        assert not missing, (
            f"engine.metrics() keys undocumented in "
            f"docs/observability.md: {sorted(missing)}"
        )
        # The dispatch mirror's per-kind stat fields are part of the
        # contract too (bench lines carry them verbatim).
        undocumented_fields = [
            f for f in SUMMARY_FIELDS if f"`{f}`" not in doc
        ]
        assert not undocumented_fields, undocumented_fields
    finally:
        engine.stop()


def test_fleet_plane_surface_is_documented():
    """Doc-sync guard (fleet-plane extension): the fleet-level rollup
    keys, the per-link ledger fields, and the new operator commands
    (`llmctl top` / `llmctl audit` / `llmctl bench compare`) must land
    in docs/observability.md's "Fleet plane" section, with matching
    suite rows in docs/testing.md and the README pointer — the same
    discipline as the metric-name guard above."""
    from dynamo_exp_tpu.telemetry.fleet import FleetView, LinkStats

    doc = _observability_doc()
    assert "## Fleet plane" in doc
    assert "## KV conservation auditor" in doc
    for cmd in ("llmctl top", "llmctl audit", "llmctl bench compare"):
        assert cmd in doc, f"{cmd!r} undocumented in docs/observability.md"
    # Every fleet rollup key and ledger link field is contract surface
    # (llmctl top, SimReport.fleet, and the planner consume them).
    rollup = FleetView.from_snapshots({}).rollup()
    missing = [k for k in rollup if f"`{k}`" not in doc and k not in doc]
    assert not missing, (
        f"fleet rollup keys undocumented in docs/observability.md: {missing}"
    )
    link = LinkStats("a", "b").to_dict()
    missing_link = [k for k in link if f"`{k}`" not in doc and k not in doc]
    assert not missing_link, missing_link

    import os

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "..", "docs", "testing.md")) as f:
        testing = f.read()
    for row in ("test_fleet.py", "test_kv_ledger.py", "llmctl audit"):
        assert row in testing, f"{row!r} missing from docs/testing.md"
    with open(os.path.join(here, "..", "README.md")) as f:
        readme = f.read()
    assert "Fleet plane" in readme


def test_anatomy_plane_surface_is_documented():
    """Doc-sync guard (anatomy-plane extension): the request-anatomy
    component vocabulary, the fingerprint/drift surface, and the new
    operator commands must land in docs/observability.md, with the
    suite row in docs/testing.md and the README pointer — same
    discipline as the fleet-plane guard above."""
    import os

    from dynamo_exp_tpu.telemetry.anatomy import COMPONENTS

    doc = _observability_doc()
    assert "## Request anatomy" in doc
    assert "## Workload fingerprint" in doc
    # Every anatomy component name is contract surface: prometheus
    # label, metrics() mirror key, bench-line field, --why waterfall.
    missing = [c for c in COMPONENTS if c not in doc]
    assert not missing, (
        f"anatomy components undocumented in docs/observability.md: "
        f"{missing}"
    )
    for cmd in (
        "llmctl slow",
        "llmctl fingerprint",
        "llmctl trace 4f1f2a --trace-file /tmp/trace.jsonl --why",
        "DYN_WORKLOAD_REF",
        "dynamo_slo_burn_rate",
    ):
        assert cmd in doc, f"{cmd!r} undocumented in docs/observability.md"

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "..", "docs", "testing.md")) as f:
        testing = f.read()
    assert "test_anatomy.py" in testing
    with open(os.path.join(here, "..", "README.md")) as f:
        readme = f.read()
    assert "Request anatomy" in readme
    assert "llmctl fingerprint" in readme
