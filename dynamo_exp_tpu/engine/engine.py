"""The TPU execution engine: continuous batching on a paged KV cache.

This replaces the reference's wrapped GPU engines (vLLM/sglang/TRT-LLM —
``/root/reference/lib/engines/``, SURVEY.md §2.3/§2.9) with an in-process
JAX engine:

- **Two compiled programs** drive everything: a decode step over all
  active slots (B = max_decode_slots, T = 1) and a bucketed prefill
  (B = 1, T ∈ prefill_buckets). Static shapes, no recompiles in steady
  state; KV pools are donated so XLA updates them in place in HBM.
- **The host loop is the scheduler** (reference's "hard part #3",
  SURVEY.md §7): stop flags, admissions, page allocation, and KV event
  emission all happen between steps on the loop thread — never inside a
  compiled region.
- **Prefix caching is free at the attention level**: reused pages are
  already resident; prefill just starts its positions after the cached
  prefix (write-then-gather attention reads them like any other page).
- **Tensor parallelism** comes from param/cache shardings over the
  engine's mesh; XLA inserts the ICI collectives.

The engine exposes the same ``AsyncEngine`` seam the rest of the stack
uses (``BackendInput`` dict in → ``LLMEngineOutput`` dict stream out), so
the preprocessor/backend/router layers are engine-agnostic, matching the
reference's ``ExecutionContext`` contract (``lib/llm/src/backend.rs:60``).
"""

from __future__ import annotations

import asyncio
import logging
import queue
import threading
from functools import partial
from typing import AsyncIterator, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.config import ModelConfig
from ..models.llama import (
    Params,
    forward,
    init_kv_cache,
    init_params,
    kv_cache_shardings,
    param_shardings,
)
from ..ops.sampling import apply_penalties, sample_tokens
from ..parallel.mesh import build_mesh
from ..protocols.common import BackendInput, FinishReason, LLMEngineOutput
from ..runtime.engine import AsyncEngine, AsyncEngineContext, ResponseStream
from .config import EngineConfig
from .kv_manager import KvEvent, KvPageManager
from .offload import CopyStream, HostKvPool
from .scheduler import RemoteKv, Scheduler, SeqState, Sequence

log = logging.getLogger(__name__)


class TPUEngine(AsyncEngine):
    """Continuous-batching paged-KV engine on a TPU mesh."""

    def __init__(
        self,
        cfg: EngineConfig,
        params: Params | None = None,
        mesh: Mesh | None = None,
        kv_event_cb: Callable[[KvEvent], None] | None = None,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.mesh = mesh or build_mesh(tp=cfg.tp, sp=cfg.sp)
        mcfg = cfg.model

        def sharding(spec):
            return NamedSharding(self.mesh, spec)

        if params is None:
            params = init_params(jax.random.PRNGKey(seed), mcfg)
        self.params = jax.device_put(
            params,
            jax.tree.map(
                sharding,
                param_shardings(mcfg),
                is_leaf=lambda x: isinstance(x, P),
            ),
        )
        kspec, vspec = kv_cache_shardings()
        k, v = init_kv_cache(
            mcfg, cfg.num_pages, cfg.page_size, dtype=cfg.kv_dtype_jnp
        )
        self.k_cache = jax.device_put(k, sharding(kspec))
        self.v_cache = jax.device_put(v, sharding(vspec))

        self.host_pool: HostKvPool | None = None
        self.copy_stream: CopyStream | None = None
        on_evict = None
        if cfg.host_cache_pages > 0:
            page_shape = (
                mcfg.num_layers,
                cfg.page_size,
                mcfg.num_kv_heads,
                mcfg.head_dim_,
            )
            self.host_pool = HostKvPool(
                cfg.host_cache_pages, page_shape, cfg.kv_dtype_jnp
            )

            # The CopyStream (a live thread) is created by start(), so a
            # constructed-but-never-started engine owns no threads.
            def on_evict(pid: int, seq_hash: int) -> None:
                # Dispatch the on-device gather now (stream order protects
                # it from the next donated forward); the CopyStream thread
                # blocks on the transfer and commits into the host pool.
                k_pg, v_pg = self._gather_page(self.k_cache, self.v_cache, pid)
                self.copy_stream.offload(seq_hash, k_pg, v_pg)

        self.kv = KvPageManager(
            cfg.num_pages,
            cfg.page_size,
            event_cb=kv_event_cb if cfg.enable_kv_events else None,
            host_pool=self.host_pool,
            on_evict=on_evict,
        )
        self.sched = Scheduler(cfg, self.kv)

        # Per-page movement kernels, shared by the G2 offload tier and
        # the disaggregation KV handoff (gather → wire / wire → inject).
        self._gather_page = jax.jit(lambda k, v, pid: (k[:, pid], v[:, pid]))
        self._inject_page = jax.jit(
            lambda k, v, pid, hk, hv: (
                k.at[:, pid].set(hk),
                v.at[:, pid].set(hv),
            ),
            donate_argnums=(0, 1),
        )

        B, V = cfg.max_decode_slots, mcfg.vocab_size
        self._counts = jnp.zeros((B, V), jnp.int32)  # penalty bookkeeping
        self._rng = jax.random.PRNGKey(seed + 1)
        self._decode_fn = self._build_decode()
        self._prefill_fns: dict[int, Callable] = {}  # bucket T -> compiled fn
        self._reset_row = jax.jit(
            lambda c, i: c.at[i].set(0), donate_argnums=(0,)
        )

        self._submit_q: queue.Queue[Sequence] = queue.Queue()
        self._wake = threading.Event()
        self._running = False
        self._thread: threading.Thread | None = None
        self.steps = 0  # decode step counter (metrics)

    # ----------------------------------------------------------- compiled fns
    def _build_decode(self):
        cfg, mcfg = self.cfg, self.cfg.model

        @partial(jax.jit, donate_argnums=(1, 2, 7))
        def decode_step(params, k, v, tokens, positions, page_table, rng, counts,
                        temp, top_k, top_p, freq_pen, pres_pen, rep_pen):
            logits, k, v = forward(
                params, mcfg, tokens[:, None], positions[:, None], page_table, k, v
            )
            logits = logits[:, 0]  # [B, V]
            logits = apply_penalties(logits, counts, freq_pen, pres_pen, rep_pen)
            rng, sub = jax.random.split(rng)
            next_tok = sample_tokens(logits, sub, temp, top_k, top_p)
            active = (positions >= 0).astype(jnp.int32)
            counts = counts.at[jnp.arange(counts.shape[0]), next_tok].add(active)
            return next_tok, k, v, rng, counts

        return decode_step

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        mcfg = self.cfg.model

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_step(params, k, v, tokens, positions, page_table, rng,
                         last_idx, temp, top_k, top_p):
            logits, k, v = forward(params, mcfg, tokens, positions, page_table, k, v)
            last = jax.lax.dynamic_index_in_dim(logits[0], last_idx, keepdims=True)
            rng, sub = jax.random.split(rng)
            tok = sample_tokens(last, sub, temp[None], top_k[None], top_p[None])[0]
            return tok, k, v, rng

        self._prefill_fns[bucket] = prefill_step
        return prefill_step

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._running:
            return
        if self.host_pool is not None and self.copy_stream is None:
            # stop() tears the copy stream down; a restarted engine needs
            # a live one before the first eviction fires on_evict.
            self.copy_stream = CopyStream(self.host_pool)
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="tpu-engine-loop", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=30)
            self._thread = None
        if self.copy_stream is not None:
            self.copy_stream.stop()
            self.copy_stream = None

    # ------------------------------------------------------------ AsyncEngine
    async def generate(
        self,
        request: dict | BackendInput,
        context: AsyncEngineContext | None = None,
        remote_kv: RemoteKv | None = None,
    ) -> ResponseStream[dict]:
        if not self._running:
            self.start()
        ctx = context or AsyncEngineContext()
        binput = (
            request
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()

        def emit(tokens: list[int], reason: FinishReason | None) -> None:
            loop.call_soon_threadsafe(out_q.put_nowait, (tokens, reason))

        seq = Sequence(
            request_id=ctx.id,
            prompt=list(binput.token_ids),
            stop=binput,
            emit=emit,
            is_cancelled=lambda: ctx.is_stopped,
            remote_kv=remote_kv,
        )
        self._submit_q.put(seq)
        self._wake.set()
        prompt_tokens = len(binput.token_ids)

        async def _gen() -> AsyncIterator[dict]:
            completion = 0
            while True:
                tokens, reason = await out_q.get()
                if tokens:
                    completion += len(tokens)
                    yield LLMEngineOutput(token_ids=tokens).to_dict()
                if reason is not None:
                    yield LLMEngineOutput(
                        finish_reason=reason,
                        prompt_tokens=prompt_tokens,
                        completion_tokens=completion,
                    ).to_dict()
                    return

        return ResponseStream(_gen(), ctx)

    async def prefill_extract(
        self,
        request: dict | BackendInput,
        context: AsyncEngineContext | None = None,
    ) -> tuple[int, list]:
        """Run prefill only and hand back (first_token, kv_pages).

        This is the prefill-worker side of disaggregation: the prompt's
        KV pages (host-bounced numpy, one (k, v) pair per page) travel to
        the decode worker, which injects them via ``generate(...,
        remote_kv=...)``. The pages also stay registered locally, so
        repeated prompts prefix-hit this worker's pool.
        """
        if not self._running:
            self.start()
        ctx = context or AsyncEngineContext()
        binput = (
            request.model_copy(deep=True)  # never mutate the caller's object
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        binput.stop_conditions.max_tokens = 1  # prefill produces one token
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def extract_cb(token: int, pages: list) -> None:
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result((token, pages))
            )

        def emit(tokens: list[int], reason: FinishReason | None) -> None:
            if reason in (FinishReason.ERROR, FinishReason.CANCELLED):
                loop.call_soon_threadsafe(
                    lambda: fut.done()
                    or fut.set_exception(RuntimeError(f"prefill failed: {reason}"))
                )

        seq = Sequence(
            request_id=ctx.id,
            prompt=list(binput.token_ids),
            stop=binput,
            emit=emit,
            is_cancelled=lambda: ctx.is_stopped,
            extract_cb=extract_cb,
        )
        self._submit_q.put(seq)
        self._wake.set()
        return await fut

    # -------------------------------------------------------------- the loop
    def _loop(self) -> None:
        try:
            while self._running:
                if not self.sched.has_work() and self._submit_q.empty():
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                self._drain_submissions()
                self._poll_cancellations()
                seq = self.sched.next_prefill()
                if seq is not None:
                    if seq.remote_kv is not None:
                        self._run_remote_inject(seq)
                    else:
                        self._run_prefill(seq)
                elif self.sched.active_count > 0:
                    self._run_decode()
        except Exception:  # engine death must not hang clients
            log.exception("engine loop crashed; failing in-flight requests")
            self._running = False
            self._fail_all()
            raise

    def _drain_submissions(self) -> None:
        while True:
            try:
                self.sched.submit(self._submit_q.get_nowait())
            except queue.Empty:
                return

    def _poll_cancellations(self) -> None:
        for s in list(self.sched.slots):
            if s is not None and s.is_cancelled():
                self.sched.finish(s, FinishReason.CANCELLED)

    def _fail_all(self) -> None:
        for s in list(self.sched.slots):
            if s is not None:
                self.sched.finish(s, FinishReason.ERROR)
        while self.sched.waiting:
            s = self.sched.waiting.popleft()
            s.emit([], FinishReason.ERROR)
        while not self._submit_q.empty():
            try:
                self._submit_q.get_nowait().emit([], FinishReason.ERROR)
            except queue.Empty:
                break

    # ---------------------------------------------------------------- prefill
    def _apply_uploads(self, seq: Sequence) -> None:
        """Re-inject G2 host pages into their fresh device pages before
        the compute that attends over them (dispatch order on the device
        stream makes this safe without explicit sync)."""
        for pid, _h, hk, hv in seq.pending_uploads:
            self.k_cache, self.v_cache = self._inject_page(
                self.k_cache, self.v_cache, pid, jnp.asarray(hk), jnp.asarray(hv)
            )
        seq.pending_uploads = []

    def _finish_first_token(self, seq: Sequence, token: int) -> None:
        """Shared tail of the two admission paths (computed prefill or
        remote-KV injection): record + announce the first sampled token."""
        self._counts = self._reset_row(self._counts, seq.slot)
        seq.tokens.append(token)
        seq.generated = 1
        self.sched.register_full_pages(seq)
        if seq.extract_cb is not None:
            seq.extract_cb(token, self._extract_prompt_pages(seq))
        reason = self.sched.check_stop(seq, token)
        seq.emit([token], None)
        if reason is not None:
            self.sched.finish(seq, reason)

    def _extract_prompt_pages(self, seq: Sequence) -> list:
        """Host-bounce every prompt page (incl. the partial tail) for the
        disaggregation handoff. Runs on the engine loop thread: the
        prefill worker's job is exactly this transfer."""
        ps = self.cfg.page_size
        n_pages = (len(seq.prompt) + ps - 1) // ps
        pages = []
        for pid in seq.page_ids[:n_pages]:
            k_pg, v_pg = self._gather_page(self.k_cache, self.v_cache, pid)
            pages.append((np.asarray(k_pg), np.asarray(v_pg)))
        return pages

    def _run_remote_inject(self, seq: Sequence) -> None:
        """Disaggregated admission: prompt KV was computed by a remote
        prefill worker — inject it and go straight to decode."""
        self._apply_uploads(seq)
        ps = self.cfg.page_size
        rk = seq.remote_kv
        n_pages = (len(seq.prompt) + ps - 1) // ps
        start = seq.cached_len // ps  # locally matched/uploaded prefix
        for i in range(start, min(n_pages, len(rk.pages))):
            hk, hv = rk.pages[i]
            self.k_cache, self.v_cache = self._inject_page(
                self.k_cache,
                self.v_cache,
                seq.page_ids[i],
                jnp.asarray(hk),
                jnp.asarray(hv),
            )
        self._finish_first_token(seq, rk.first_token)

    def _run_prefill(self, seq: Sequence) -> None:
        cfg = self.cfg
        self._apply_uploads(seq)
        suffix = seq.prompt[seq.cached_len :]
        bucket = cfg.bucket_for(len(suffix))
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, : len(suffix)] = suffix
        positions = np.full((1, bucket), -1, np.int32)
        positions[0, : len(suffix)] = np.arange(
            seq.cached_len, seq.cached_len + len(suffix)
        )
        table = np.zeros((1, cfg.max_pages_per_seq), np.int32)
        table[0, : len(seq.page_ids)] = seq.page_ids

        so = seq.stop.sampling_options
        fn = self._prefill_fn(bucket)
        tok, self.k_cache, self.v_cache, self._rng = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(table),
            self._rng,
            len(suffix) - 1,
            jnp.float32(so.temperature if so.temperature is not None else 0.0),
            jnp.int32(so.top_k or 0),
            jnp.float32(so.top_p if so.top_p is not None else 1.0),
        )
        self._finish_first_token(seq, int(tok))

    # ----------------------------------------------------------------- decode
    def _run_decode(self) -> None:
        cfg = self.cfg
        B = cfg.max_decode_slots
        tokens = np.zeros(B, np.int32)
        positions = np.full(B, -1, np.int32)
        table = np.zeros((B, cfg.max_pages_per_seq), np.int32)
        temp = np.zeros(B, np.float32)
        top_k = np.zeros(B, np.int32)
        top_p = np.ones(B, np.float32)
        freq = np.zeros(B, np.float32)
        pres = np.zeros(B, np.float32)
        rep = np.ones(B, np.float32)

        stepped: list[Sequence] = []
        for i, seq in enumerate(self.sched.slots):
            if seq is None or seq.state is not SeqState.ACTIVE:
                continue
            wpos = len(seq.tokens) - 1  # position of the token being fed
            if not self.sched.ensure_decode_page(seq, wpos):
                continue  # pool dry: this slot idles one step
            tokens[i] = seq.last_token()
            positions[i] = wpos
            table[i, : len(seq.page_ids)] = seq.page_ids
            so = seq.stop.sampling_options
            temp[i] = so.temperature if so.temperature is not None else 0.0
            top_k[i] = so.top_k or 0
            top_p[i] = so.top_p if so.top_p is not None else 1.0
            freq[i] = so.frequency_penalty or 0.0
            pres[i] = so.presence_penalty or 0.0
            rep[i] = so.repetition_penalty or 1.0
            stepped.append(seq)
        if not stepped:
            # Everything stalled on the page pool; yield briefly.
            self._wake.wait(timeout=0.001)
            return

        next_tok, self.k_cache, self.v_cache, self._rng, self._counts = (
            self._decode_fn(
                self.params,
                self.k_cache,
                self.v_cache,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(table),
                self._rng,
                self._counts,
                jnp.asarray(temp),
                jnp.asarray(top_k),
                jnp.asarray(top_p),
                jnp.asarray(freq),
                jnp.asarray(pres),
                jnp.asarray(rep),
            )
        )
        self.steps += 1
        sampled = np.asarray(next_tok)
        for seq in stepped:
            token = int(sampled[seq.slot])
            seq.tokens.append(token)
            seq.generated += 1
            self.sched.register_full_pages(seq)
            reason = self.sched.check_stop(seq, token)
            seq.emit([token], None)
            if reason is not None:
                self.sched.finish(seq, reason)

    # --------------------------------------------------------------- metrics
    def metrics(self) -> dict:
        m = self.sched.metrics()
        if self.host_pool is not None:
            m["host_cache_resident"] = self.host_pool.resident
            m["host_cache_hits"] = self.host_pool.hits
            m["host_cache_stores"] = self.host_pool.stores
        return m
