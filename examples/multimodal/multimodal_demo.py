"""Multimodal demo graph: EncodeWorker → soft-token prefill.

Reference parity: ``/root/reference/examples/multimodal/`` (encode
worker feeds image features to the LLM worker, which prefixes them to
the prompt). The LLM side here drives the model layer directly with
``forward(token_embeds=...)``: image patch embeddings followed by the
prompt's token embeddings, one greedy decode step.

    python -m dynamo_exp_tpu.sdk.serve \
        examples.multimodal.multimodal_demo:VisionChat --start-coordinator
"""

from __future__ import annotations

import logging

from dynamo_exp_tpu.sdk import async_on_start, depends, endpoint, service

from .components.encode_worker import EncodeWorker

logger = logging.getLogger(__name__)


@service(dynamo={"namespace": "multimodal"}, resources={"tpu": 1})
class VisionChat:
    """Consumes encoded image features as a soft-token prefix."""

    encoder = depends(EncodeWorker, endpoint="encode")

    preset: str = "tiny"

    def __init__(self):
        self.params = None
        self.cfg = None

    @async_on_start
    async def build(self) -> None:
        import jax

        from dynamo_exp_tpu.models import PRESETS, init_params

        self.cfg = PRESETS[self.preset]
        self.params = init_params(jax.random.PRNGKey(0), self.cfg)

    @endpoint()
    async def generate(self, request: dict):
        import jax.numpy as jnp
        import numpy as np

        from dynamo_exp_tpu.models import init_kv_cache
        from dynamo_exp_tpu.models.llama import forward

        stream = await self.encoder.generate(
            {"pixels": request["pixels"], "shape": request.get("shape")}
        )
        features = None
        async for item in stream:
            features = np.asarray(item["image_features"], np.float32)
        prompt = list(request.get("token_ids", []))

        # Soft-token prefix: [image patches] + [prompt embeddings].
        embed = np.asarray(self.params["embed"], np.float32)
        feats = features[:, : self.cfg.hidden_size]
        x = np.concatenate([feats, embed[prompt]], axis=0)[None]
        T = x.shape[1]
        ps = 16
        pmax = (T + ps - 1) // ps
        k, v = init_kv_cache(self.cfg, num_pages=pmax + 1, page_size=ps)
        logits, _, _ = forward(
            self.params,
            self.cfg,
            jnp.zeros((1, T), jnp.int32),
            jnp.arange(T, dtype=jnp.int32)[None],
            jnp.arange(1, pmax + 1, dtype=jnp.int32)[None],
            k,
            v,
            token_embeds=jnp.asarray(x),
        )
        next_token = int(jnp.argmax(logits[0, -1]))
        yield {"n_image_tokens": int(feats.shape[0]), "next_token": next_token}
