"""Host-side KV page pool: allocation, content-addressed prefix sharing,
LRU eviction, and KV event emission.

Capability parity with the reference's KV block manager
(``/root/reference/lib/llm/src/kv/reuse.rs:50-760`` — the
``AvailableBlocks`` match/take/update actor — and ``kv/manager.rs:22-168``
G1/G2 tiers), redesigned for the TPU engine:

- Device pages live in the paged pools allocated by ``models/llama.py``;
  this manager only tracks *ids* — all data movement happens inside the
  jitted forward (writes) or via host offload (``offload.py``).
- Reuse is content-addressed by the chained sequence hash of each full
  page (``tokens.py``), indexed in a radix tree
  (:class:`~dynamo_exp_tpu.kv.PrefixIndex`), so a new request's prompt
  prefix maps onto pages already resident in HBM. Matched pages are
  ref-counted and **shared across live sequences** — a page leaves G1
  only at refcount zero (docs/prefix_sharing.md).
- Sharing extends to pages still *being filled*: prompt pages are
  registered at allocation (``filled=False`` until their prefill chunk
  is dispatched), so a burst of same-prefix admissions attaches one
  copy instead of prefilling N. A filler that dies orphans its pending
  pages; a waiting sharer claims and re-fills them (deterministic
  forward ⇒ identical content).
- A prompt ending *inside* a registered block can attach that block as
  a shared partial tail (radix ``partial_match``); the first divergent
  write — the sequence's own decode into the shared page — triggers
  copy-on-write (:meth:`make_private`).
- Every registered/evicted full page emits a KV event (stored/removed)
  through a callback — the feed for the KV-aware router's radix index
  (reference: ``lib/llm/src/kv_router/publisher.rs:34-139``).
"""

from __future__ import annotations

import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Sequence

from ..kv import PrefixIndex
from ..tokens import compute_block_hashes_for_seq

if TYPE_CHECKING:
    import numpy as np

    from .offload import HostKvPool


@dataclass
class PageRecord:
    page_id: int
    seq_hash: int | None = None  # None until the page is registered
    ref_count: int = 0
    # Content state for allocation-time registration: a prompt page is
    # registered (matchable) the moment it is allocated, but ``filled``
    # flips only once the write that materializes it has been
    # *dispatched* (stream order then protects later readers). While
    # unfilled, ``filler`` names the request responsible for the write;
    # a dead filler leaves it "" (orphaned) for a sharer to claim.
    filled: bool = True
    filler: str = ""


@dataclass
class Allocation:
    """Result of ``allocate_sequence``.

    ``page_ids`` covers ceil(len(tokens)/page_size) pages; ``cached_len``
    counts tokens whose KV the sequence need not recompute — G1-matched
    + G2-uploaded full pages plus a shared partial tail, capped at
    len(tokens)-1 so prefill always computes the last token's logits;
    ``cached_pages`` counts the registered full pages among them (the
    scheduler's hash-chain resume point); ``uploads`` lists
    (page_id, seq_hash, k_page, v_page) host pages the engine must
    inject before prefill; ``wait_fill`` lists attached pages another
    sequence is still filling (the engine defers this sequence's first
    prefill dispatch until they are filled); ``shared_tail`` is the
    (page_id, covered_tokens) partial-tail attach, COWed before the
    first divergent write; ``hashes`` are the chained sequence hashes
    of every full prompt page (computed once here so the scheduler
    never rehashes the prompt)."""

    page_ids: list[int]
    cached_len: int
    uploads: list
    hashes: list[int]
    cached_pages: int = 0
    wait_fill: list[int] = field(default_factory=list)
    shared_tail: tuple[int, int] | None = None


@dataclass
class KvLease:
    """A pin on extracted pages during a disaggregation KV handoff.

    The prefill worker extracts a sequence's pages for the wire while
    the owning sequence finishes — without a lease the pages would park
    in the reclaimable LRU and could be evicted (or, under the handoff
    contract, be considered delivered) before the decode worker confirms
    receipt. The lease takes one extra reference per page; delivery
    confirmation (``confirm_lease``) releases it, and the reaper
    (``reap_expired``) reclaims orphans when the decode instance dies
    between extract and inject — so failover never strands HBM. The
    decode side reuses the same pin for suffix-only transfers: matched
    local prefix pages stay resident between the routing decision and
    the admission that re-references them.

    State machine (docs/fault_tolerance.md "Resumable streams"):
    GRANTED → CONFIRMED (transfer acked end-to-end) | EXPIRED (reaped).
    """

    lease_id: str
    page_ids: list[int]
    expires_at: float  # manager-clock seconds


@dataclass
class KvEvent:
    """Stored/removed notification for the router's radix index."""

    kind: str  # "stored" | "removed"
    seq_hashes: list[int]
    parent_hash: int | None = None
    token_blocks: list[list[int]] | None = None  # only on stored
    ts: float = field(default_factory=time.time)


class KvPageManager:
    """Tracks ownership and reuse of the device page pool by id.

    Not thread-safe by design: owned by the engine loop thread, the same
    single-writer discipline the reference uses for its block pool actor.
    """

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        event_cb: Callable[[KvEvent], None] | None = None,
        host_pool: "HostKvPool | None" = None,
        on_evict: Callable[[int, int], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
        sharing: bool = True,
        g3_store=None,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        self.event_cb = event_cb
        self.clock = clock
        # Fleet-wide prefix sharing (docs/prefix_sharing.md). False is
        # the private-copy baseline: no cross-sequence reuse at all —
        # every admission materializes its own pages (bench.py's
        # --prefix-sweep comparison arm).
        self.sharing = sharing
        # G2 tier: evicted device pages are offloaded (via ``on_evict``,
        # which the engine wires to a device gather + CopyStream) and
        # matched back in from ``host_pool`` on later prompts.
        self.host_pool = host_pool
        self.on_evict = on_evict
        # G3 tier (docs/fault_tolerance.md "Durable KV"): the persistent
        # checksummed page store. Admission extends a G1+G2 match into
        # it; each fetched page is checksum-verified by the store and
        # promoted through the host pool (a corrupt page quarantines
        # there and just shortens the restored prefix).
        self.g3_store = g3_store
        self._records: dict[int, PageRecord] = {
            i: PageRecord(i) for i in range(num_pages)
        }
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        # seq_hash -> page_id for every registered full page still
        # resident, plus the radix index over the same blocks (the
        # structure the partial-tail lookup and the router share).
        self._by_hash: dict[int, int] = {}
        self.index = PrefixIndex()
        # Zero-ref registered pages, LRU order (oldest first).
        self._reclaimable: OrderedDict[int, None] = OrderedDict()
        # Disaggregation handoff leases, by lease id (single-writer like
        # everything else here: only the engine loop thread touches them).
        self._leases: dict[str, KvLease] = {}
        self.lease_reclaimed_pages = 0  # pages freed by the reaper
        # Metrics counters.
        self.hits = 0
        self.misses = 0
        # G2 (host offload tier) hit/miss: of the pages a prompt needed
        # beyond its G1 device match, how many the host tier supplied.
        self.offload_hits = 0
        self.offload_misses = 0
        # Prefix-sharing counters (docs/prefix_sharing.md): page-granular
        # hit breakdown at admission, copy-on-write copies, and the
        # high-water mark of resident pages (bench.py --prefix-sweep
        # reads pages-per-request off the peak).
        # "persist" = pages restored from the G3 store at admission —
        # the restart re-attachment proof the identity tests read.
        self.prefix_hits = {"shared": 0, "restore": 0, "persist": 0, "miss": 0}
        self.cow_copies = 0
        self.peak_active_pages = 0
        # Incrementally tracked (refcount 1→2 / 2→1 crossings), so the
        # gauge and the bench's high-water never scan the pool.
        self.live_shared = 0
        self.peak_shared_pages = 0
        # Conservation ledger (docs/observability.md "KV conservation
        # auditor"): pages currently referenced (ref_count >= 1) and the
        # refcount grand total, both maintained at the SAME transitions
        # that move pages between the free list, the parked LRU, and the
        # held set — so ``ledger_check`` is pure counter arithmetic
        # (O(1), no pool scan, no device work). A double-release or a
        # lost reference breaks the arithmetic within the very mutation
        # that caused it.
        self._held_pages = 0
        self._ref_total = 0
        # Leases reclaimed by the most recent reap_expired() call —
        # (lease_id, pages) pairs the engine reads to close lease spans.
        self.last_reaped: list[tuple[str, int]] = []

    # ---------------------------------------------------------------- stats
    @property
    def free_pages(self) -> int:
        return len(self._free) + len(self._reclaimable)

    @property
    def active_pages(self) -> int:
        return self.num_pages - self.free_pages

    @property
    def usage(self) -> float:
        return self.active_pages / max(self.num_pages, 1)

    @property
    def shared_pages(self) -> int:
        """Pages currently attached by more than one holder."""
        return self.live_shared

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def offload_hit_rate(self) -> float:
        total = self.offload_hits + self.offload_misses
        return self.offload_hits / total if total else 0.0

    def gauges(self) -> dict:
        """Engine-level KV gauges for the telemetry registry."""
        return {
            "hbm_page_occupancy": self.usage,
            "offload_hit_rate": self.offload_hit_rate(),
            "kv_shared_pages": self.shared_pages,
            # G2 tier occupancy (docs/engine_perf.md "Predictive KV
            # tiering"): host-resident pages, so fleet views see
            # host-tier pressure (mirrored as dynamo_kv_host_pages).
            "kv_host_pages": self.host_pool.resident if self.host_pool else 0,
            # G3 tier occupancy (docs/fault_tolerance.md "Durable KV"):
            # store-resident pages (mirrored as dynamo_kv_store_pages).
            "kv_store_pages": self.g3_store.resident if self.g3_store else 0,
        }

    def _note_active(self) -> None:
        active = self.active_pages
        if active > self.peak_active_pages:
            self.peak_active_pages = active

    # ------------------------------------------------------------ allocation
    def match_prefix(
        self, tokens: Sequence[int], require_filled: bool = False
    ) -> tuple[list[int], list[int]]:
        """Longest resident prefix of ``tokens`` in full pages.

        Returns (page_ids, seq_hashes) of the matched prefix — does NOT
        take references; call ``allocate_sequence`` to commit.
        ``require_filled`` stops the walk at the first page whose
        content has not been dispatched yet (the disagg pin path must
        only count bytes that exist)."""
        return self._match_hashes(
            compute_block_hashes_for_seq(tokens, self.page_size),
            require_filled=require_filled,
        )

    def _match_hashes(
        self, hashes: list[int], require_filled: bool = False
    ) -> tuple[list[int], list[int]]:
        pages: list[int] = []
        matched: list[int] = []
        for h in self.index.match_hashes(hashes):
            pid = self._by_hash.get(h)
            if pid is None:
                break  # index/by-hash drift would be a bug; stay safe
            if require_filled and not self._records[pid].filled:
                break
            pages.append(pid)
            matched.append(h)
        return pages, matched

    def allocate_sequence(
        self, tokens: Sequence[int], max_pages: int, request_id: str = ""
    ) -> Allocation | None:
        """Pages for a new sequence: attach the longest device-resident
        (G1) shared prefix — including pages still being filled and a
        partial tail inside a registered block — extend it from the
        host tier (G2), then fresh pages for the rest of the prompt.
        Freshly allocated full prompt pages are registered immediately
        (``filled=False``) so concurrent same-prefix admissions share
        them instead of re-prefilling.

        Returns an ``Allocation`` or None if the pool can't satisfy the
        request right now (caller re-queues).
        """
        ps = self.page_size
        n_tokens = len(tokens)
        need_total = (n_tokens + ps - 1) // ps
        if need_total > max_pages:
            return None  # exceeds per-sequence capacity; caller must reject
        hashes = compute_block_hashes_for_seq(tokens, ps)
        if self.sharing:
            matched_pages, matched_hashes = self._match_hashes(hashes)
        else:
            matched_pages, matched_hashes = [], []
        # Extend the match into the host tier — match first (no copies);
        # pages are fetched only once the allocation is known to succeed,
        # so a pool-exhausted retry loop never repeats the memcpys.
        g2_hashes: list[int] = []
        if self.sharing and self.host_pool is not None:
            g2_hashes = self.host_pool.match_chain(hashes[len(matched_pages) :])
        # Extend further into the G3 persistent store (membership only;
        # bytes are checksum-verified at fetch below) — the path a
        # returning conversation re-attaches through after a restart.
        g3_hashes: list[int] = []
        if self.sharing and self.g3_store is not None:
            g3_hashes = self.g3_store.match_chain(
                hashes[len(matched_pages) + len(g2_hashes) :]
            )
        # Shared partial tail: the prompt ends inside a block some other
        # sequence registered — attach that page read-shared; the owner
        # COWs it before its first divergent (decode) write.
        shared_tail: tuple[int, int] | None = None
        tail_tokens = tokens[(n_tokens // ps) * ps :]
        if (
            self.sharing
            and tail_tokens
            and not g2_hashes
            and not g3_hashes
            and len(matched_pages) == n_tokens // ps
        ):
            parent = matched_hashes[-1] if matched_hashes else None
            hit = self.index.partial_match(parent, tail_tokens)
            if hit is not None:
                tpid = self._by_hash.get(hit[0])
                if tpid is not None:
                    shared_tail = (tpid, hit[1])
        need_fresh = need_total - len(matched_pages) - (1 if shared_tail else 0)
        # Matched parked pages are about to leave the reclaimable LRU
        # (_ref_page below); counting them as takeable here would let
        # _take_free pop an empty LRU and crash the engine loop.
        attach = matched_pages + ([shared_tail[0]] if shared_tail else [])
        parked_matches = sum(
            1 for pid in attach if self._records[pid].ref_count == 0
        )
        if need_fresh > self._available_for_take() - parked_matches:
            return None
        # fetch() copies each page out under the pool lock, so a
        # concurrent LRU eviction can't corrupt it before injection; a
        # miss (evicted since match) just shortens the restored prefix.
        host_pages: list[tuple[int, "np.ndarray", "np.ndarray"]] = []
        for h in g2_hashes:
            data = self.host_pool.fetch(h)
            if data is None:
                break
            host_pages.append((h, data[0], data[1]))
        # G3 fetches only extend an UNBROKEN chain (a mid-chain G2
        # eviction makes the G3 tail unmatchable). Each fetch is
        # checksum-verified inside the store — a corrupt page
        # quarantines, returns None, and the restored prefix shortens
        # (the block re-prefills from the prompt, token-identically).
        # Verified bytes promote through the host pool so sibling
        # admissions hit RAM next time.
        persist_pages: list[tuple[int, "np.ndarray", "np.ndarray"]] = []
        if len(host_pages) == len(g2_hashes):
            for h in g3_hashes:
                data = self.g3_store.fetch(h)
                if data is None:
                    break
                persist_pages.append((h, data[0], data[1]))
                if self.host_pool is not None:
                    self.host_pool.store(h, data[0], data[1])
        restore_pages = host_pages + persist_pages
        for pid in attach:  # commit the reuse
            self._ref_page(pid)
        fresh = [self._take_free() for _ in range(need_fresh)]
        uploads = [
            (fresh[j], h, k, v) for j, (h, k, v) in enumerate(restore_pages)
        ]
        # Register this sequence's own full prompt pages NOW (pending
        # fill): a same-prefix request admitted next can share them.
        # Upload pages are registered by the scheduler with the chain
        # walk it already does (_register_uploads); pages past the
        # uploads are this request's to compute.
        if self.sharing:
            for j in range(len(restore_pages), need_fresh):
                block_idx = len(matched_pages) + j
                if (block_idx + 1) * ps > n_tokens:
                    break  # partial tail block: never registered early
                h = hashes[block_idx]
                if h in self._by_hash:
                    continue  # racing duplicate content: stay private
                rec = self._records[fresh[j]]
                rec.seq_hash = h
                rec.filled = False
                rec.filler = request_id
                self._by_hash[h] = fresh[j]
                block = list(tokens[block_idx * ps : (block_idx + 1) * ps])
                parent = hashes[block_idx - 1] if block_idx else None
                self.index.insert(parent, h, tokens=block, payload=fresh[j])
                if self.event_cb:
                    self.event_cb(
                        KvEvent(
                            "stored", [h], parent_hash=parent,
                            token_blocks=[block],
                        )
                    )
        self.hits += len(attach) + len(restore_pages)
        self.misses += need_fresh - len(restore_pages)
        if self.host_pool is not None:
            self.offload_hits += len(host_pages)
            self.offload_misses += need_fresh - len(restore_pages)
        self.prefix_hits["shared"] += len(attach)
        self.prefix_hits["restore"] += len(host_pages)
        self.prefix_hits["persist"] += len(persist_pages)
        self.prefix_hits["miss"] += need_fresh - len(restore_pages)
        cached_pages = len(matched_pages) + len(restore_pages)
        cached = cached_pages * ps + (shared_tail[1] if shared_tail else 0)
        cached = min(cached, n_tokens - 1)
        wait_fill = [
            pid for pid in attach if not self._records[pid].filled
        ]
        page_ids = matched_pages + fresh
        if shared_tail:
            page_ids = matched_pages + fresh + [shared_tail[0]]
        self._note_active()
        return Allocation(
            page_ids,
            cached,
            uploads,
            hashes,
            cached_pages=cached_pages,
            wait_fill=wait_fill,
            shared_tail=shared_tail,
        )

    def allocate_page(self) -> int | None:
        """One fresh page (decode crossing a page boundary)."""
        if self._available_for_take() < 1:
            return None
        pid = self._take_free()
        self._note_active()
        return pid

    # -------------------------------------------- tiering accessors
    def match_resident_hashes(self, hashes: list[int]) -> list[int]:
        """Device-resident (G1) prefix of a block-hash chain — the
        footprint forecast's and the prefetch planner's read-only
        match (no refs taken)."""
        pages, _ = self._match_hashes(hashes)
        return pages

    def page_ref(self, page_id: int) -> int:
        return self._records[page_id].ref_count

    def page_hash(self, page_id: int) -> int | None:
        return self._records[page_id].seq_hash

    def resident_page(self, seq_hash: int) -> int | None:
        """The device page holding this registered, *filled* block (or
        None) — swap-in re-attaches through this instead of fetching
        from the host tier when the content never left the device."""
        pid = self._by_hash.get(seq_hash)
        if pid is None or not self._records[pid].filled:
            return None
        return pid

    def attach_page(self, page_id: int) -> None:
        """Take one reference on a resident page (swap-in re-attach of
        a still-parked or shared block)."""
        self._ref_page(page_id)

    def lease_active(self, lease_id: str) -> bool:
        return lease_id in self._leases

    # ------------------------------------------------------------- lifecycle
    def register_full_page(
        self,
        page_id: int,
        seq_hash: int,
        parent_hash: int | None = None,
        tokens: list[int] | None = None,
        content_ready: bool = True,
    ) -> None:
        """A page just got its page_size-th token (or was pre-registered
        for a pending fill): make it reusable and announce it to the
        router index. ``content_ready=False`` registers the page as
        matchable while its data is still on the way (G2 uploads before
        injection); the engine marks it filled at the injecting
        dispatch."""
        rec = self._records[page_id]
        if rec.seq_hash == seq_hash:
            if content_ready:
                rec.filled = True
                rec.filler = ""
            return
        if rec.seq_hash is not None:
            # Re-registration under different content (tests / page
            # repurposing): the stale index entry must go first.
            self._unregister(page_id)
        # A different page may already hold this content (two requests with
        # the same prompt racing); keep the first registration authoritative.
        if seq_hash not in self._by_hash:
            rec.seq_hash = seq_hash
            rec.filled = content_ready
            self._by_hash[seq_hash] = page_id
            self.index.insert(
                parent_hash, seq_hash, tokens=tokens, payload=page_id
            )
            if self.event_cb:
                self.event_cb(
                    KvEvent(
                        "stored",
                        [seq_hash],
                        parent_hash=parent_hash,
                        token_blocks=[tokens] if tokens else None,
                    )
                )

    # ------------------------------------------------------- fill lifecycle
    def mark_filled(self, page_ids: Sequence[int]) -> None:
        """The write materializing these pages has been dispatched:
        waiting sharers may dispatch reads (device stream order now
        protects them)."""
        for pid in page_ids:
            rec = self._records[pid]
            rec.filled = True
            rec.filler = ""

    def begin_fill(self, page_id: int, request_id: str) -> None:
        """Mark a registered page as pending content from ``request_id``
        (G2 upload awaiting its inject dispatch)."""
        rec = self._records[page_id]
        rec.filled = False
        rec.filler = request_id

    def fill_state(self, page_id: int) -> str:
        """"filled" | "pending" (live filler) | "orphaned" (filler died
        before dispatching the write; a sharer must claim + re-fill)."""
        rec = self._records[page_id]
        if rec.filled:
            return "filled"
        return "pending" if rec.filler else "orphaned"

    def claim_fill(self, page_id: int, request_id: str) -> None:
        """A sharer adopts an orphaned page: it will re-prefill the
        block itself (deterministic forward ⇒ identical content)."""
        rec = self._records[page_id]
        if not rec.filled:
            rec.filler = request_id

    def abort_fills(self, request_id: str, page_ids: Sequence[int]) -> None:
        """The filler is going away (finish/cancel/preempt) with writes
        not yet dispatched: orphan its pending pages so sharers can
        claim them. Call BEFORE releasing the refs."""
        for pid in page_ids:
            rec = self._records[pid]
            if not rec.filled and rec.filler == request_id:
                rec.filler = ""

    def make_private(self, page_id: int) -> int | None:
        """Copy-on-write entry point: the caller is about to write a
        divergent value into ``page_id``.

        - Sole holder: the page just leaves the index (content offloads
          to G2 first — it is still a valid block for future prompts)
          and is returned as-is.
        - Shared: allocate a replacement page; the caller must device-
          copy content old→new, swap its table entry, and drop its ref
          on the old page. Returns None when the pool is dry (caller
          treats it as a hard stall and retries).
        """
        rec = self._records[page_id]
        if rec.ref_count <= 1:
            if rec.seq_hash is not None:
                if self.on_evict is not None and rec.filled:
                    self.on_evict(page_id, rec.seq_hash)
                self._unregister(page_id)
            rec.filled = True
            rec.filler = ""
            return page_id
        new_pid = self.allocate_page()
        if new_pid is None:
            return None
        self.cow_copies += 1
        return new_pid

    def release_sequence(self, page_ids: Sequence[int]) -> None:
        """Sequence finished: drop refs. Registered *filled* pages park
        in the LRU (still matchable); unfilled registered pages — a
        fill that never happened — unregister (their bytes are garbage)
        and return to the free list with the rest."""
        for pid in page_ids:
            rec = self._records[pid]
            if rec.ref_count > 0:
                rec.ref_count -= 1
                self._ref_total -= 1
                if rec.ref_count == 1:
                    self.live_shared -= 1
                if rec.ref_count == 0:
                    self._held_pages -= 1
            if rec.ref_count == 0:
                if rec.seq_hash is not None and rec.filled:
                    self._reclaimable[pid] = None
                    self._reclaimable.move_to_end(pid)
                else:
                    if rec.seq_hash is not None:
                        self._unregister(pid)
                    self._free.append(pid)

    # ---------------------------------------------------------------- leases
    @property
    def active_leases(self) -> int:
        return len(self._leases)

    def grant_lease(self, page_ids: Sequence[int], ttl_s: float) -> str:
        """Pin ``page_ids`` (one extra ref each) for a KV handoff in
        flight; returns the lease id the wire protocol carries. Must be
        called while the pages are still referenced (before the owning
        sequence is released), i.e. on the engine loop thread — or, for
        the decode-side suffix-transfer pin, on registered resident
        pages the match just returned."""
        for pid in page_ids:
            self._ref_page(pid)
        lease = KvLease(
            lease_id=uuid.uuid4().hex,
            page_ids=list(page_ids),
            expires_at=self.clock() + ttl_s,
        )
        self._leases[lease.lease_id] = lease
        return lease.lease_id

    def confirm_lease(self, lease_id: str) -> bool:
        """Delivery confirmed: drop the lease's pins. Registered pages
        park in the reclaimable LRU exactly as a finished sequence's
        would. Unknown/already-reaped ids are a no-op (the confirm raced
        the reaper)."""
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return False
        self.release_sequence(lease.page_ids)
        return True

    def reap_expired(self, now: float | None = None) -> int:
        """Reclaim every expired lease's pages; returns pages freed.
        Engine-loop-thread only (mutates the free lists).
        ``last_reaped`` names the leases this call reclaimed so the
        caller can close their trace spans (`llmctl trace` shows the
        reap as the lease's terminal hop)."""
        now = self.clock() if now is None else now
        reclaimed = 0
        self.last_reaped = []
        for lid in [
            lid for lid, l in self._leases.items() if now >= l.expires_at
        ]:
            lease = self._leases.pop(lid)
            self.release_sequence(lease.page_ids)
            reclaimed += len(lease.page_ids)
            self.last_reaped.append((lid, len(lease.page_ids)))
        self.lease_reclaimed_pages += reclaimed
        return reclaimed

    # ------------------------------------------------- conservation ledger
    def ledger_check(self) -> list[str]:
        """Cheap conservation invariant (docs/observability.md "KV
        conservation auditor"): every page is exactly one of
        {free, parked, held}, and refcount totals conserve across
        attach/COW/release/evict/reap. Pure counter arithmetic over
        already-maintained ints — O(1), no pool scan, no host sync —
        so the engine loop runs it every iteration. Returns violation
        descriptions (empty = conserved); ``audit()`` is the on-demand
        full scan that names the leaking holder."""
        violations: list[str] = []
        free, parked = len(self._free), len(self._reclaimable)
        held = self._held_pages
        if free + parked + held != self.num_pages:
            violations.append(
                f"page conservation broken: free={free} parked={parked} "
                f"held={held} sum={free + parked + held} != "
                f"pool={self.num_pages}"
            )
        if not 0 <= self.live_shared <= held:
            violations.append(
                f"shared-page count out of range: live_shared="
                f"{self.live_shared} held={held}"
            )
        # Every held page carries >= 1 ref; every shared page >= 2.
        if self._ref_total < held + self.live_shared:
            violations.append(
                f"refcount total below holder floor: ref_total="
                f"{self._ref_total} < held={held} + shared="
                f"{self.live_shared}"
            )
        if self._ref_total < 0 or held < 0:
            violations.append(
                f"negative ledger counter: ref_total={self._ref_total} "
                f"held={held}"
            )
        lease_pins = sum(len(l.page_ids) for l in self._leases.values())
        if lease_pins > self._ref_total:
            violations.append(
                f"lease pins exceed refcount total: lease_pins="
                f"{lease_pins} ref_total={self._ref_total}"
            )
        return violations

    def audit(self, holders: dict[str, Sequence[int]] | None = None) -> dict:
        """Full on-demand conservation audit (``llmctl audit``): scan
        the pool, classify every page into exactly one state, and — when
        ``holders`` maps holder names (``seq:<request_id>``) to the page
        ids they believe they hold — cross-check per-page refcounts
        against the holder set so a leak is *named*, not just counted.
        Leases are joined in automatically as ``lease:<id>`` holders.
        Read-only; safe (best-effort) from a non-loop thread for flight
        dumps."""
        free_list = list(self._free)
        free = set(free_list)
        parked = set(self._reclaimable)
        expected: dict[int, list[str]] = {}
        all_holders: dict[str, Sequence[int]] = dict(holders or {})
        for lid, lease in self._leases.items():
            all_holders[f"lease:{lid}"] = lease.page_ids
        for name, pids in all_holders.items():
            for pid in pids:
                expected.setdefault(pid, []).append(name)
        counts = {"free": 0, "parked": 0, "active": 0, "shared": 0,
                  "leased": sum(len(l.page_ids) for l in self._leases.values())}
        violations: list[dict] = []

        def flag(pid: int, kind: str, detail: str) -> None:
            violations.append(
                {
                    "page": pid,
                    "kind": kind,
                    "detail": detail,
                    "holders": sorted(expected.get(pid, [])),
                }
            )

        if len(free) != len(free_list):
            dupes = sorted(
                pid for pid in free if free_list.count(pid) > 1
            )
            for pid in dupes:
                flag(pid, "double_release", "page appears twice in the free list")
        for pid, rec in self._records.items():
            states = []
            if pid in free:
                states.append("free")
            if pid in parked:
                states.append("parked")
            if rec.ref_count > 0:
                states.append("active")
            if len(states) != 1:
                flag(
                    pid, "state_overlap" if states else "unaccounted",
                    f"page in states {states or ['none']} "
                    f"(ref_count={rec.ref_count})",
                )
            if rec.ref_count > 0:
                counts["active"] += 1
                if rec.ref_count >= 2:
                    counts["shared"] += 1
            elif pid in parked:
                counts["parked"] += 1
            elif pid in free:
                counts["free"] += 1
            if rec.ref_count < 0:
                flag(pid, "negative_refcount", f"ref_count={rec.ref_count}")
            want = len(expected.get(pid, []))
            if all_holders and rec.ref_count != want and (
                rec.ref_count > 0 or want > 0
            ):
                kind = "leaked_ref" if rec.ref_count > want else "lost_ref"
                flag(
                    pid, kind,
                    f"ref_count={rec.ref_count} but {want} live holder(s)",
                )
        for check in self.ledger_check():
            violations.append(
                {"page": None, "kind": "counter", "detail": check,
                 "holders": []}
            )
        return {
            "ok": not violations,
            "counts": counts,
            "pool": self.num_pages,
            "leases": len(self._leases),
            "held_pages": self._held_pages,
            "ref_total": self._ref_total,
            "violations": violations,
        }

    # -------------------------------------------------------------- internal
    def _available_for_take(self) -> int:
        return len(self._free) + len(self._reclaimable)

    def _ref_page(self, pid: int) -> None:
        rec = self._records[pid]
        if rec.ref_count == 0:
            self._reclaimable.pop(pid, None)
            self._held_pages += 1
        rec.ref_count += 1
        self._ref_total += 1
        if rec.ref_count == 2:
            self.live_shared += 1
            if self.live_shared > self.peak_shared_pages:
                self.peak_shared_pages = self.live_shared
        self._note_active()

    def _take_free(self) -> int:
        if self._free:
            pid = self._free.pop()
        else:
            # Evict the least-recently-used parked page.
            pid, _ = self._reclaimable.popitem(last=False)
            self._evict(pid)
        rec = self._records[pid]
        rec.ref_count = 1
        self._held_pages += 1
        self._ref_total += 1
        rec.seq_hash = None
        rec.filled = True
        rec.filler = ""
        return pid

    def _evict(self, pid: int) -> None:
        rec = self._records[pid]
        if rec.seq_hash is not None:
            if self.on_evict is not None:
                # Offload to G2 before the page can be overwritten: the
                # engine dispatches the on-device gather synchronously
                # here (stream order protects it from the next forward).
                self.on_evict(pid, rec.seq_hash)
            self._unregister(pid)

    def _unregister(self, pid: int) -> None:
        """Drop a page's registration from the hash map + radix index
        and announce the removal. Content is NOT offloaded here — the
        eviction path does that first when the bytes are worth keeping."""
        rec = self._records[pid]
        if rec.seq_hash is None:
            return
        self._by_hash.pop(rec.seq_hash, None)
        self.index.remove(rec.seq_hash)
        if self.event_cb:
            self.event_cb(KvEvent("removed", [rec.seq_hash]))
        rec.seq_hash = None
        rec.filled = True
        rec.filler = ""
