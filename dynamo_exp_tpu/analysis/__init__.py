"""dynlint: AST invariant checkers for the engine's hot-path contracts.

Four properties this codebase leans on live here as machine-checked
rules instead of CHANGES.md folklore (docs/static_analysis.md):

- ``host-sync`` — declared hot-path zones (engine loop, scheduler,
  offload/CopyStream, dispatch profiler) may not add implicit
  device→host syncs; every legitimate sync point is an inline-waived,
  reviewed allowlist entry. Complements the *runtime* sync-spy in
  tests/test_dispatch_profile.py: the spy counts syncs in one driven
  scenario, the checker polices every code path at diff time.
- ``determinism`` — seed-deterministic zones (``sim/``, ``spec/``, the
  chaos schedules, flight-recorder payload construction) may not read
  wall clocks, unseeded RNGs, ``uuid``/``os.urandom``, or leak
  ``id()``/``hash()`` into recorded payloads. Complements the runtime
  bit-identity tests (tests/test_sim.py, tests/test_flight.py).
- ``thread-ownership`` — a manifest declares which engine attributes
  only the loop thread may mutate and which surfaces are cross-thread
  handoffs (``_submit_q``, ``_lease_confirm_q``, …); writes to
  loop-owned state on call paths reachable from non-loop entry points
  are flagged, as are accesses to lock-guarded state outside its
  ``with lock:`` block.
- ``recompile-hazard`` — dispatch sites that key compiled-variant
  caches (``_ragged_fns``, the
  gather/scatter page movers) must derive shape-carrying key
  components through the ``*_bucket_for`` helpers; a raw dynamic int
  in a variant key is a recompile storm waiting for an unlucky load.

Everything here is pure stdlib (``ast`` + ``re``): ``python -m
dynamo_exp_tpu.analysis`` runs with no jax/pydantic installed, which is
what lets the CI lint job gate on it without the full dependency image.
"""

from .core import Finding, Zone, parse_waivers
from .determinism import DeterminismChecker
from .host_sync import HostSyncChecker
from .ownership import LockManifest, ThreadManifest, ThreadOwnershipChecker
from .recompile import RecompileHazardChecker, VariantSiteManifest
from .runner import RULES, WAIVER_TOKENS, lint_tree, run_cli

__all__ = [
    "Finding",
    "Zone",
    "parse_waivers",
    "HostSyncChecker",
    "DeterminismChecker",
    "ThreadOwnershipChecker",
    "ThreadManifest",
    "LockManifest",
    "RecompileHazardChecker",
    "VariantSiteManifest",
    "RULES",
    "WAIVER_TOKENS",
    "lint_tree",
    "run_cli",
]
