"""Sim-in-the-loop autotuner over the engine's knob surface
(docs/tuning.md; ``llmctl tune``).

Four stages, each its own module:

- :mod:`.space` — the declarative knob registry: every tunable
  ``EngineConfig`` / ``PlannerConfig`` / ``SloTargets`` / ``SimConfig``
  field with its grid and sim-vs-live applicability, guarded against
  config drift by a registry-walk test.
- :mod:`.search` — deterministic seeded coordinate descent with a
  successive-halving rung, evaluating candidates in the cluster
  simulator against a workload-fingerprint target, journaling every
  trial as resumable JSONL.
- :mod:`.validate` — top-K candidates re-run on the live tiny harness;
  sim-vs-live rank agreement (Kendall tau + top-1) gates the
  recommendation.
- :mod:`.artifact` — the emitted config artifact: knob overrides +
  provenance + target fingerprint + matching AOT manifest, loadable
  straight into an engine boot or a planner config catalog.
"""

from .artifact import (
    ARTIFACT_VERSION,
    build_artifact,
    catalog_entry_from_artifact,
    engine_config_from_artifact,
    load_artifact,
    manifest_from_artifact,
    resolved_live_knobs,
    write_artifact,
)
from .search import (
    SearchSettings,
    TuneResult,
    TuneTarget,
    composite_objective,
    evaluate,
    load_journal,
    run_search,
    target_from_fingerprint,
    target_from_trace,
    top_candidates,
)
from .space import (
    KNOB_BY_NAME,
    KNOBS,
    config_hash,
    render_knob_table,
    space_digest,
)
from .validate import kendall_tau, validate_candidates

__all__ = [
    "ARTIFACT_VERSION",
    "KNOBS",
    "KNOB_BY_NAME",
    "SearchSettings",
    "TuneResult",
    "TuneTarget",
    "build_artifact",
    "catalog_entry_from_artifact",
    "composite_objective",
    "config_hash",
    "engine_config_from_artifact",
    "evaluate",
    "kendall_tau",
    "load_artifact",
    "load_journal",
    "manifest_from_artifact",
    "render_knob_table",
    "resolved_live_knobs",
    "run_search",
    "space_digest",
    "target_from_fingerprint",
    "target_from_trace",
    "top_candidates",
    "validate_candidates",
    "write_artifact",
]
