"""Endpoint client: tracks live instances and issues streaming requests.

Capability parity with ``/root/reference/lib/runtime/src/component/client.rs``:
a dynamic client watches discovery for membership changes (lease expiry
drops instances instantly); a static client uses a fixed instance list.
Routing policies live in :mod:`push_router`.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator

from .annotated import Annotated
from .engine import AsyncEngineContext
from .runtime import Runtime
from .transports.base import Discovery, InstanceInfo, RequestPlane

logger = logging.getLogger(__name__)


class Client:
    def __init__(self, request_plane: RequestPlane):
        self.request_plane = request_plane
        self._instances: list[InstanceInfo] = []
        self._changed = asyncio.Event()
        self._watch_task: asyncio.Task | None = None

    # --- construction -------------------------------------------------
    @classmethod
    def new_static(
        cls, request_plane: RequestPlane, instances: list[InstanceInfo]
    ) -> "Client":
        c = cls(request_plane)
        c._instances = list(instances)
        c._changed.set()
        return c

    @classmethod
    async def new_dynamic(
        cls,
        runtime: Runtime,
        discovery: Discovery,
        request_plane: RequestPlane,
        endpoint_path: str,
    ) -> "Client":
        c = cls(request_plane)

        async def _watch() -> None:
            async for snapshot in discovery.watch_instances(endpoint_path):
                c._instances = snapshot
                c._changed.set()

        c._instances = await discovery.list_instances(endpoint_path)
        c._watch_task = runtime.spawn(_watch())
        return c

    # --- membership ---------------------------------------------------
    @property
    def instances(self) -> list[InstanceInfo]:
        return self._instances

    def instance_ids(self) -> list[int]:
        return [i.instance_id for i in self._instances]

    async def wait_for_instances(self, n: int = 1, timeout: float | None = None) -> None:
        async def _wait() -> None:
            while len(self._instances) < n:
                self._changed.clear()
                await self._changed.wait()

        await asyncio.wait_for(_wait(), timeout)

    def instance(self, instance_id: int) -> InstanceInfo:
        for i in self._instances:
            if i.instance_id == instance_id:
                return i
        raise KeyError(f"instance {instance_id} is not live")

    # --- requests -----------------------------------------------------
    async def generate_to(
        self,
        instance: InstanceInfo,
        request: dict,
        context: AsyncEngineContext | None = None,
    ) -> AsyncIterator[Annotated]:
        """Issue a request to one instance; yields Annotated frames.

        Error frames raise ``EngineError`` so callers see remote failures
        as exceptions unless they iterate the raw stream themselves.
        """
        ctx = context or AsyncEngineContext()
        frames = await self.request_plane.request_stream(instance, request, ctx)

        async def _gen() -> AsyncIterator[Annotated]:
            async for frame in frames:
                ann = Annotated.from_dict(frame)
                if ann.is_error():
                    raise EngineError(ann.error_message() or "remote engine error")
                yield ann

        return _gen()

    def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()


class EngineError(RuntimeError):
    """A remote engine reported an error frame in its response stream."""
