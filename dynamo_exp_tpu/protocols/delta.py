"""Delta generators: backend step outputs -> OpenAI stream chunks.

Capability parity with ``/root/reference/lib/llm/src/protocols/openai/
chat_completions/delta.rs`` and ``completions/delta.rs``.
"""

from __future__ import annotations

from .common import FinishReason
from .openai import (
    ChatChoiceDelta,
    ChatCompletionChunk,
    ChatStreamChoice,
    CompletionChoice,
    CompletionChunk,
    Usage,
    new_request_id,
    now_unix,
)


class ChatDeltaGenerator:
    """Stateful converter for one chat request's response stream."""

    def __init__(self, model: str, request_id: str | None = None, index: int = 0):
        self.model = model
        self.id = request_id or new_request_id("chatcmpl")
        self.created = now_unix()
        self.index = index
        self._sent_role = False

    def role_chunk(self) -> ChatCompletionChunk:
        self._sent_role = True
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatStreamChoice(
                    index=self.index, delta=ChatChoiceDelta(role="assistant")
                )
            ],
        )

    def text_chunk(self, text: str, logprobs=None) -> ChatCompletionChunk:
        delta = ChatChoiceDelta(content=text)
        if not self._sent_role:
            delta.role = "assistant"
            self._sent_role = True
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatStreamChoice(
                    index=self.index, delta=delta, logprobs=logprobs
                )
            ],
        )

    def finish_chunk(self, reason: FinishReason) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                ChatStreamChoice(
                    index=self.index,
                    delta=ChatChoiceDelta(),
                    finish_reason=reason.to_openai(),
                )
            ],
        )

    def usage_chunk(self, prompt_tokens: int, completion_tokens: int) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[],
            usage=Usage(
                prompt_tokens=prompt_tokens,
                completion_tokens=completion_tokens,
                total_tokens=prompt_tokens + completion_tokens,
            ),
        )


class CompletionDeltaGenerator:
    """Stateful converter for one text-completion request's stream."""

    def __init__(self, model: str, request_id: str | None = None, index: int = 0):
        self.model = model
        self.id = request_id or new_request_id("cmpl")
        self.created = now_unix()
        self.index = index

    def text_chunk(self, text: str, logprobs=None) -> CompletionChunk:
        return CompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                CompletionChoice(
                    index=self.index, text=text, logprobs=logprobs
                )
            ],
        )

    def finish_chunk(self, reason: FinishReason) -> CompletionChunk:
        return CompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[
                CompletionChoice(
                    index=self.index, text="", finish_reason=reason.to_openai()
                )
            ],
        )

    def usage_chunk(self, prompt_tokens: int, completion_tokens: int) -> CompletionChunk:
        return CompletionChunk(
            id=self.id,
            created=self.created,
            model=self.model,
            choices=[],
            usage=Usage(
                prompt_tokens=prompt_tokens,
                completion_tokens=completion_tokens,
                total_tokens=prompt_tokens + completion_tokens,
            ),
        )
