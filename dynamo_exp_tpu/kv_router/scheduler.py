"""KV-aware worker selection.

Capability parity with ``/root/reference/lib/llm/src/kv_router/scheduler.rs``
(:88-310): pluggable ``WorkerSelector`` over live endpoint metrics +
overlap scores; the default cost extends the reference's

    logit = 2 * overlap_ratio - gpu_cache_usage - normalized_active

with random tie-breaking (scheduler.rs:239-310) by a **queue-depth
penalty**: ``- queue_weight * waiting / total_slots``, fed from the
``num_requests_waiting`` gauge the metrics aggregator already scrapes.
Without it a saturated instance with a deep waiting queue but a good
prefix overlap keeps attracting work (NetKV's observation, PAPERS.md);
with it, load sheds toward idle instances once the backlog outweighs
the overlap advantage.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Protocol

from .protocols import ForwardPassMetrics, OverlapScores


@dataclass
class ProcessedEndpoints:
    """Live worker set + metrics snapshot (reference: ``scoring.rs:24``)."""

    metrics: dict[int, ForwardPassMetrics] = field(default_factory=dict)

    @property
    def worker_ids(self) -> list[int]:
        return list(self.metrics)


class WorkerSelector(Protocol):
    def select_worker(
        self,
        endpoints: ProcessedEndpoints,
        overlaps: OverlapScores,
        isl_tokens: int,
        block_size: int,
    ) -> tuple[int, int]:
        """Returns (worker_id, overlap_blocks). Raises if no workers."""
        ...


class NoWorkersError(RuntimeError):
    pass


class DefaultWorkerSelector:
    def __init__(
        self, rng: random.Random | None = None, queue_weight: float = 1.0
    ):
        self.rng = rng or random.Random()
        # Weight of the queue-depth penalty (waiting / total_slots). 0
        # restores the pure reference cost; 1.0 makes one slot-envelope
        # of backlog as repulsive as a fully busy decode batch.
        self.queue_weight = queue_weight

    def select_worker(
        self,
        endpoints: ProcessedEndpoints,
        overlaps: OverlapScores,
        isl_tokens: int,
        block_size: int,
    ) -> tuple[int, int]:
        if not endpoints.metrics:
            raise NoWorkersError("no live workers")
        best_ids: list[int] = []
        best_logit = -float("inf")
        for wid, m in endpoints.metrics.items():
            matched = overlaps.scores.get(wid, 0)
            overlap_ratio = (
                matched * block_size / isl_tokens if isl_tokens > 0 else 0.0
            )
            normalized_active = (
                m.request_active_slots / m.request_total_slots
                if m.request_total_slots
                else 0.0
            )
            normalized_waiting = (
                m.num_requests_waiting / m.request_total_slots
                if m.request_total_slots
                else float(m.num_requests_waiting > 0)
            )
            logit = (
                2.0 * overlap_ratio
                - m.gpu_cache_usage_perc
                - normalized_active
                - self.queue_weight * normalized_waiting
            )
            if logit > best_logit + 1e-12:
                best_logit = logit
                best_ids = [wid]
            elif abs(logit - best_logit) <= 1e-12:
                best_ids.append(wid)
        wid = self.rng.choice(best_ids)
        return wid, overlaps.scores.get(wid, 0)
