"""Tests for token blocks and chained hashing."""

from dynamo_exp_tpu.tokens import (
    TokenBlockSequence,
    compute_block_hash,
    compute_block_hashes_for_seq,
)


def test_block_completion_and_partial():
    seq = TokenBlockSequence(block_size=4)
    completed = seq.extend([1, 2, 3])
    assert completed == []
    assert seq.partial_tokens == [1, 2, 3]
    block = seq.push(4)
    assert block is not None
    assert block.tokens == (1, 2, 3, 4)
    assert seq.partial_tokens == []
    assert len(seq) == 4


def test_sequence_hash_chains_prefix():
    a = TokenBlockSequence(range(8), block_size=4)
    b = TokenBlockSequence(list(range(4)) + [9, 9, 9, 9], block_size=4)
    # Same first block -> same first sequence hash.
    assert a.blocks[0].sequence_hash == b.blocks[0].sequence_hash
    # Different second block -> different chained hash.
    assert a.blocks[1].sequence_hash != b.blocks[1].sequence_hash
    # Chained hash differs from local hash of the same content.
    assert a.blocks[1].sequence_hash != a.blocks[1].block_hash


def test_same_block_content_different_prefix_differs():
    # Block [5,6,7,8] appears at position 1 in both, but prefixes differ.
    a = TokenBlockSequence([1, 2, 3, 4, 5, 6, 7, 8], block_size=4)
    b = TokenBlockSequence([9, 9, 9, 9, 5, 6, 7, 8], block_size=4)
    assert a.blocks[1].block_hash == b.blocks[1].block_hash
    assert a.blocks[1].sequence_hash != b.blocks[1].sequence_hash


def test_compute_block_hashes_for_seq_matches_sequence():
    tokens = list(range(300))
    seq = TokenBlockSequence(tokens, block_size=64)
    assert compute_block_hashes_for_seq(tokens, 64) == seq.block_hashes()
    assert len(seq.block_hashes()) == 4  # 300 // 64


def test_on_block_callback():
    events = []
    seq = TokenBlockSequence(block_size=2, on_block=events.append)
    seq.extend([1, 2, 3, 4, 5])
    assert [b.tokens for b in events] == [(1, 2), (3, 4)]


def test_truncate():
    seq = TokenBlockSequence(range(10), block_size=4)
    seq.truncate(6)
    assert len(seq) == 6
    assert len(seq.blocks) == 1
    assert seq.partial_tokens == [4, 5]
    # Hashes are recomputed consistently.
    assert seq.blocks[0].sequence_hash == TokenBlockSequence(range(4), block_size=4).blocks[0].sequence_hash


def test_hash_seed_matters():
    assert compute_block_hash([1, 2, 3], seed=1) != compute_block_hash([1, 2, 3], seed=2)


def test_truncate_does_not_replay_on_block_events():
    events = []
    seq = TokenBlockSequence(range(8), block_size=4, on_block=events.append)
    assert len(events) == 2
    seq.truncate(6)
    assert len(events) == 2  # no replayed "stored" events
    seq.extend([6, 7])
    assert len(events) == 3  # but new completions still fire
