"""Transport interfaces: discovery (control plane) and request plane.

The reference splits its distributed fabric into planes
(``/root/reference/lib/runtime/src/transports/``): etcd for
discovery/leases/watches, NATS for the request push plane, raw TCP for
response streams. We keep the same plane split behind two small
interfaces so the whole stack runs either fully in-process (static mode,
unit tests) or over our self-hosted coordinator + TCP planes — no external
etcd/NATS services required.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Awaitable, Callable

from ..engine import AsyncEngineContext

# A served endpoint handler: request dict -> stream of Annotated dicts.
Handler = Callable[[dict, AsyncEngineContext], AsyncIterator[dict]]
# A stats handler: () -> metrics dict (merged into the instance's stats).
StatsHandler = Callable[[], dict]


@dataclass(frozen=True)
class EndpointAddress:
    """Hierarchical endpoint id: ``{namespace}/components/{component}/{name}``."""

    namespace: str
    component: str
    name: str

    @property
    def subject(self) -> str:
        return f"{self.namespace}.{self.component}.{self.name}"

    @property
    def path(self) -> str:
        return f"{self.namespace}/components/{self.component}/endpoints/{self.name}"

    @classmethod
    def from_url(cls, url: str) -> "EndpointAddress":
        """Parse ``dyn://ns.component.endpoint``."""
        body = url.removeprefix("dyn://")
        parts = body.split(".")
        if len(parts) != 3:
            raise ValueError(f"expected dyn://ns.component.endpoint, got {url!r}")
        return cls(*parts)


@dataclass
class InstanceInfo:
    """One live instance of an endpoint, as published to discovery."""

    address: EndpointAddress
    instance_id: int
    transport: str = "inproc"  # "inproc" | "tcp"
    transport_address: str = ""  # host:port for tcp
    metadata: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "namespace": self.address.namespace,
            "component": self.address.component,
            "name": self.address.name,
            "instance_id": self.instance_id,
            "transport": self.transport,
            "transport_address": self.transport_address,
            "metadata": self.metadata,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "InstanceInfo":
        return cls(
            address=EndpointAddress(d["namespace"], d["component"], d["name"]),
            instance_id=d["instance_id"],
            transport=d.get("transport", "inproc"),
            transport_address=d.get("transport_address", ""),
            metadata=d.get("metadata", {}),
        )


class Lease(abc.ABC):
    """A liveness lease; revoking (or process death) removes registrations."""

    @property
    @abc.abstractmethod
    def lease_id(self) -> int: ...

    @abc.abstractmethod
    async def revoke(self) -> None: ...

    @abc.abstractmethod
    def is_valid(self) -> bool: ...


class Discovery(abc.ABC):
    """Control plane: endpoint registry with leases + watches, and a small
    KV store with watch support (model entries, disagg config)."""

    @abc.abstractmethod
    async def register_instance(self, info: InstanceInfo, lease: Lease | None = None) -> Lease: ...

    @abc.abstractmethod
    async def create_lease(self, ttl_s: float | None = None) -> Lease: ...

    @abc.abstractmethod
    async def deregister_instance(self, instance_id: int) -> None:
        """Remove one instance without touching its lease."""

    @abc.abstractmethod
    async def list_instances(self, prefix: str) -> list[InstanceInfo]: ...

    @abc.abstractmethod
    def watch_instances(self, prefix: str) -> "AsyncIterator[list[InstanceInfo]]":
        """Yields the full live-instance snapshot on every membership change
        (first yield is the current snapshot)."""

    # --- generic KV with watch (etcd-style) ---
    @abc.abstractmethod
    async def kv_put(self, key: str, value: bytes, lease: Lease | None = None) -> None: ...

    @abc.abstractmethod
    async def kv_create(self, key: str, value: bytes, lease: Lease | None = None) -> bool:
        """Create-if-absent; returns False if the key already exists."""

    @abc.abstractmethod
    async def kv_get(self, key: str) -> bytes | None: ...

    @abc.abstractmethod
    async def kv_get_prefix(self, prefix: str) -> dict[str, bytes]: ...

    @abc.abstractmethod
    async def kv_delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def kv_watch_prefix(self, prefix: str) -> "AsyncIterator[dict[str, bytes]]":
        """Yields the full prefix snapshot on every change (first yield is
        the current snapshot)."""

    # --- sibling-plane factories ---
    # The discovery backend knows which fabric the process is on, so it is
    # the factory for the other control-plane services (events, queues,
    # object store). Defaults are in-process; coordinator-backed discovery
    # overrides them to ride the same server connection.
    def _new_event_plane(self) -> "EventPlane":
        from .inproc import InProcEventPlane

        return InProcEventPlane()

    def _new_work_queue(self, name: str) -> "WorkQueue":
        from .inproc import InProcWorkQueue

        return InProcWorkQueue()

    def _new_object_store(self) -> "ObjectStore":
        from .inproc import InProcObjectStore

        return InProcObjectStore()

    def event_plane(self) -> "EventPlane":
        plane = getattr(self, "_event_plane", None)
        if plane is None:
            plane = self._event_plane = self._new_event_plane()
        return plane

    def work_queue(self, name: str) -> "WorkQueue":
        queues = getattr(self, "_work_queues", None)
        if queues is None:
            queues = self._work_queues = {}
        if name not in queues:
            queues[name] = self._new_work_queue(name)
        return queues[name]

    def object_store(self) -> "ObjectStore":
        store = getattr(self, "_object_store", None)
        if store is None:
            store = self._object_store = self._new_object_store()
        return store

    async def close(self) -> None:  # pragma: no cover - default no-op
        return None


class ServedEndpoint(abc.ABC):
    """Handle for a serving endpoint; close() drains gracefully."""

    @abc.abstractmethod
    async def close(self) -> None: ...


class RequestPlane(abc.ABC):
    """Request push + streaming response plane."""

    @abc.abstractmethod
    async def serve(
        self, info: InstanceInfo, handler: Handler, stats_handler: StatsHandler | None = None
    ) -> ServedEndpoint: ...

    @abc.abstractmethod
    async def request_stream(
        self,
        instance: InstanceInfo,
        request: dict,
        context: AsyncEngineContext,
    ) -> AsyncIterator[dict]:
        """Send one request to one instance; returns the Annotated-frame
        stream. Cancelling ``context`` propagates upstream."""

    @abc.abstractmethod
    async def scrape_stats(self, instance: InstanceInfo) -> dict:
        """Fetch the instance's live stats (load metrics)."""

    async def close(self) -> None:  # pragma: no cover - default no-op
        return None


class EventPlane(abc.ABC):
    """Fire-and-forget pub/sub by subject — the NATS-subject equivalent
    (reference publishes KV events on ``{component}.kv_events``,
    ``/root/reference/lib/llm/src/kv_router/kv_router.rs:52``)."""

    @abc.abstractmethod
    async def publish(self, subject: str, payload: dict) -> None: ...

    @abc.abstractmethod
    async def subscribe(self, subject: str) -> "AsyncIterator[dict]":
        """Returns a stream of payloads published to ``subject``. The
        subscription is fully registered before this returns: no event
        published afterwards can be missed."""

    async def close(self) -> None:  # pragma: no cover - default no-op
        return None


class WorkQueue(abc.ABC):
    """Durable-ish FIFO work queue — the JetStream work-queue equivalent
    the reference uses as its prefill queue
    (``/root/reference/examples/llm/utils/nats_queue.py:1-159``)."""

    @abc.abstractmethod
    async def push(self, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def pull(self, timeout_s: float | None = None) -> bytes | None:
        """Pop the oldest item; blocks up to ``timeout_s`` (None = forever),
        returns None on timeout."""

    @abc.abstractmethod
    async def size(self) -> int: ...


class ObjectStore(abc.ABC):
    """Bucketed blob store — the NATS object-store equivalent used for
    ModelDeploymentCards (``/root/reference/lib/runtime/src/transports/nats.rs:123``)."""

    @abc.abstractmethod
    async def put(self, bucket: str, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    async def get(self, bucket: str, key: str) -> bytes | None: ...

    @abc.abstractmethod
    async def delete(self, bucket: str, key: str) -> None: ...

    @abc.abstractmethod
    async def list(self, bucket: str) -> list[str]: ...


RequestHook = Callable[[dict], Awaitable[None]]
