"""Fault-tolerance suite: the request plane under deterministic chaos.

Every scenario runs against the seeded fault-injection harness
(``runtime/transports/chaos.py``) wrapped around the in-proc planes, so
failures fire at exact, reproducible points:

- worker crash at stream start → failover retry, same result;
- worker crash mid-stream → NO retry (tokens must not duplicate);
- consecutive failures → circuit breaker opens, half-open probe closes
  it after the cooldown;
- prefill-fleet death → decode degrades to local prefill, the remote
  path's breaker opens (no more transfer-timeout burns), and recovery
  closes it;
- deadline expiry at each stage (router, request plane, prefill queue)
  stops the work before it is wasted;
- graceful drain (``llmctl drain`` KV intent → worker metadata) removes
  an instance from routing with zero failed in-flight requests;
- discovery watch flaps → the client re-subscribes and re-lists.

Run with ``make chaos`` (three fixed seeds) or plain pytest
(``-m chaos``). Seeds come from ``CHAOS_SEEDS`` (comma-separated) so CI
can sweep them without editing the file.
"""

import asyncio
import os
import random
import time

import numpy as np
import pytest

from dynamo_exp_tpu.runtime import (
    DRAIN_PREFIX,
    Annotated,
    AsyncEngineContext,
    BreakerState,
    Client,
    DeadlineExceededError,
    DistributedRuntime,
    EngineError,
    HealthTracker,
    NoHealthyInstancesError,
    PushRouter,
    ResponseStream,
    RouterMode,
)
from dynamo_exp_tpu.runtime.health import CircuitBreaker
from dynamo_exp_tpu.runtime.transports.chaos import (
    ChaosDiscovery,
    ChaosRequestPlane,
    ChaosSchedule,
    ChaosWorkQueue,
)
from dynamo_exp_tpu.runtime.transports.inproc import (
    InProcDiscovery,
    InProcRequestPlane,
    InProcWorkQueue,
)

pytestmark = pytest.mark.chaos

SEEDS = tuple(
    int(s) for s in os.environ.get("CHAOS_SEEDS", "7,21,1337").split(",")
)


# ------------------------------------------------------------------ helpers
def chaos_runtime(schedule: ChaosSchedule) -> DistributedRuntime:
    return DistributedRuntime(
        discovery=ChaosDiscovery(InProcDiscovery(), schedule),
        request_plane=ChaosRequestPlane(InProcRequestPlane(), schedule),
    )


def make_worker(wid: str, calls: list, tokens=(1, 2, 3), step_delay_s=0.0):
    async def handler(request, context):
        calls.append(wid)
        for t in tokens:
            if step_delay_s:
                await asyncio.sleep(step_delay_s)
            yield Annotated.from_data({"tok": t, "worker": wid}).to_dict()

    return handler


async def serve_two_workers(drt, calls, **worker_kw):
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    a = await ep.serve_endpoint(
        make_worker("a", calls, **worker_kw), lease=await drt.discovery.create_lease()
    )
    b = await ep.serve_endpoint(
        make_worker("b", calls, **worker_kw), lease=await drt.discovery.create_lease()
    )
    client = await ep.client()
    await client.wait_for_instances(2, timeout=2)
    return ep, a, b, client


def fast_router(client, seed=0, **kw):
    kw.setdefault("mode", RouterMode.ROUND_ROBIN)
    kw.setdefault("backoff_base_s", 0.001)
    return PushRouter(client, rng=random.Random(seed), **kw)


async def collect(stream):
    return [item async for item in stream]


# ------------------------------------------------ failover on worker crash
@pytest.mark.parametrize("seed", SEEDS)
async def test_request_survives_worker_crash_via_failover(seed):
    """Acceptance: one worker dies at dispatch; the request fails over to
    the survivor and completes with the same result, one retry counted."""
    sched = ChaosSchedule(seed)
    drt = chaos_runtime(sched)
    calls: list = []
    _, a, b, client = await serve_two_workers(drt, calls)
    router = fast_router(client, seed)
    # Round-robin picks the first-registered instance first; crash it.
    sched.fail_requests(instance_id=a.instance_id, times=1)

    out = await collect(await router.generate({}))

    assert [o["worker"] for o in out] == ["b", "b", "b"]
    assert [o["tok"] for o in out] == [1, 2, 3]
    # Exactly one retry: worker a's handler never ran, b's ran once.
    assert calls == ["b"]
    assert sched.injected == [f"request:{a.instance_id}:error"]
    # The failure registered on a's breaker; one strike, still closed.
    assert client.health.breaker(a.instance_id).consecutive_failures == 1
    assert client.health.breaker(a.instance_id).state is BreakerState.CLOSED
    assert client.health.breaker(b.instance_id).consecutive_failures == 0
    await drt.close()


async def test_no_retry_after_first_token():
    """A crash after the stream produced output must surface, not retry:
    re-dispatch would duplicate tokens."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    _, a, b, client = await serve_two_workers(drt, calls)
    router = fast_router(client, retries=5)
    sched.fail_requests(instance_id=a.instance_id, times=1, after_frames=1)

    stream = await router.generate({})
    with pytest.raises(ConnectionError, match="stream dropped"):
        await collect(stream)
    # Only the crashed worker's handler ran — no failover dispatch.
    assert calls == ["a"]
    await drt.close()


async def test_failover_exhaustion_surfaces_error():
    """Both instances dead → the original ConnectionError propagates
    after `retries` failovers, and both breakers took a strike."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    _, a, b, client = await serve_two_workers(drt, calls)
    router = fast_router(client, retries=1)
    sched.partition(a.instance_id, b.instance_id)

    with pytest.raises(ConnectionError, match="partition"):
        await router.generate({})
    assert calls == []
    assert client.health.breaker(a.instance_id).consecutive_failures == 1
    assert client.health.breaker(b.instance_id).consecutive_failures == 1
    await drt.close()


# ----------------------------------------------------------- circuit breaker
async def test_breaker_opens_blocks_and_recovers_via_half_open_probe():
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    calls: list = []
    a = await ep.serve_endpoint(make_worker("a", calls))
    t = [0.0]
    health = HealthTracker(failure_threshold=3, cooldown_s=5.0, clock=lambda: t[0])
    client = await ep.client(health=health)
    await client.wait_for_instances(1, timeout=2)
    router = fast_router(client, retries=0)

    sched.partition(a.instance_id)
    for _ in range(3):
        with pytest.raises(ConnectionError):
            await router.generate({})
    breaker = health.breaker(a.instance_id)
    assert breaker.state is BreakerState.OPEN

    # Open breaker: no dispatch reaches the dead instance at all.
    dispatches_before = len(sched.injected)
    with pytest.raises(NoHealthyInstancesError):
        await router.generate({})
    assert len(sched.injected) == dispatches_before

    # Instance recovers, cooldown elapses → half-open probe closes it.
    sched.heal()
    t[0] = 6.0
    out = await collect(await router.generate({}))
    assert [o["worker"] for o in out] == ["a", "a", "a"]
    assert breaker.state is BreakerState.CLOSED
    # And stays closed for subsequent traffic.
    await collect(await router.generate({}))
    assert calls == ["a", "a"]
    await drt.close()


async def test_half_open_failed_probe_reopens_breaker():
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    a = await ep.serve_endpoint(make_worker("a", []))
    t = [0.0]
    health = HealthTracker(failure_threshold=2, cooldown_s=5.0, clock=lambda: t[0])
    client = await ep.client(health=health)
    await client.wait_for_instances(1, timeout=2)
    router = fast_router(client, retries=0)

    sched.partition(a.instance_id)
    for _ in range(2):
        with pytest.raises(ConnectionError):
            await router.generate({})
    breaker = health.breaker(a.instance_id)
    assert breaker.state is BreakerState.OPEN
    t[0] = 6.0  # cooldown over, instance still dead: probe fails
    with pytest.raises(ConnectionError):
        await router.generate({})
    assert breaker.state is BreakerState.OPEN
    # Freshly reopened: the very next request is blocked without dispatch.
    with pytest.raises(NoHealthyInstancesError):
        await router.generate({})
    await drt.close()


# ------------------------------------------------- disagg prefill-fleet death
class StubDecodeEngine:
    """TPUEngine stand-in: real EngineConfig (shape checks stay honest),
    trivial decode that reports whether remote KV was injected."""

    def __init__(self):
        from dynamo_exp_tpu.engine import EngineConfig
        from dynamo_exp_tpu.models import TINY

        self.cfg = EngineConfig(
            model=TINY,
            max_decode_slots=2,
            page_size=8,
            num_pages=16,
            max_model_len=128,
            eos_token_ids=[],
            kv_dtype="float32",
        )

    async def generate(self, binput, ctx, remote_kv=None):
        async def _gen():
            yield {
                "token_ids": [remote_kv.first_token if remote_kv else -1],
                "remote": remote_kv is not None,
            }

        return ResponseStream(_gen(), ctx)

    def metrics(self):
        return {}


def make_disagg(sched, transfer_timeout_s=0.05, breaker=None):
    from dynamo_exp_tpu.disagg import (
        DisaggConfig,
        DisaggConfigWatcher,
        DisaggDecodeEngine,
        KvPageReceiver,
    )

    inner_queue = InProcWorkQueue()
    queue = ChaosWorkQueue(inner_queue, sched)
    recv = KvPageReceiver()
    watcher = DisaggConfigWatcher(
        InProcDiscovery(), "m", default=DisaggConfig(max_local_prefill_length=0)
    )
    engine = DisaggDecodeEngine(
        StubDecodeEngine(),
        queue,
        recv,
        watcher,
        transfer_timeout_s=transfer_timeout_s,
        breaker=breaker,
    )
    return engine, inner_queue, recv


async def run_one(engine, n_tokens=20):
    from dynamo_exp_tpu.protocols.common import BackendInput

    b = BackendInput(token_ids=list(range(3, 3 + n_tokens)))
    stream = await engine.generate(b.to_dict())
    return (await collect(stream))[0]


async def fake_prefill_service(inner_queue, cfg, first_token=9):
    """Pull one request and ship correctly-shaped zero pages back."""
    from dynamo_exp_tpu.disagg import RemotePrefillRequest, send_kv_pages

    raw = await inner_queue.pull(timeout_s=2)
    assert raw is not None
    req = RemotePrefillRequest.from_bytes(raw)
    need = (len(req.token_ids) + cfg.page_size - 1) // cfg.page_size
    shape = (cfg.model.num_layers, cfg.page_size, cfg.model.num_kv_heads * cfg.model.head_dim_)
    pages = [
        (np.zeros(shape, np.float32), np.zeros(shape, np.float32))
        for _ in range(need)
    ]
    await send_kv_pages(req.return_addr, req.request_id, first_token, pages)


async def test_prefill_fleet_death_degrades_to_local_and_breaker_recovers():
    """Acceptance: queue outage → local prefill (requests still finish),
    breaker opens after the threshold (no more queue pushes / timeout
    burns), and a healed fleet closes it via the half-open probe."""
    sched = ChaosSchedule(SEEDS[0])
    t = [0.0]
    breaker = CircuitBreaker(failure_threshold=3, cooldown_s=5.0, clock=lambda: t[0])
    engine, inner_queue, recv = make_disagg(sched, breaker=breaker)
    await recv.start()
    try:
        sched.fail_queue("push", times=-1)
        for _ in range(3):
            out = await run_one(engine)
            assert out["remote"] is False  # degraded, not dead
        assert engine.local_fallbacks == 3
        assert breaker.state is BreakerState.OPEN

        # Breaker open: the remote path is not even attempted.
        pushes_before = sum(1 for i in sched.injected if i.startswith("push"))
        out = await run_one(engine)
        assert out["remote"] is False
        assert (
            sum(1 for i in sched.injected if i.startswith("push")) == pushes_before
        )
        assert engine.local_fallbacks == 3  # no timeout burn, no fallback count

        # Fleet recovers + cooldown elapses: probe goes remote and closes.
        sched.clear()
        t[0] = 6.0
        service = asyncio.ensure_future(
            fake_prefill_service(inner_queue, engine.engine.cfg)
        )
        out = await run_one(engine)
        await asyncio.wait_for(service, 5)
        assert out["remote"] is True and out["token_ids"] == [9]
        assert breaker.state is BreakerState.CLOSED
        assert engine.remote_prefills == 1
    finally:
        await recv.close()


async def test_short_deadline_timeout_does_not_blame_prefill_fleet():
    """A transfer wait cut short by the request's own deadline must not
    count toward the remote-prefill breaker: three short-deadline
    requests would otherwise lock a healthy fleet out for a cooldown."""
    sched = ChaosSchedule(SEEDS[0])
    engine, inner_queue, recv = make_disagg(sched, transfer_timeout_s=60.0)
    await recv.start()
    try:
        from dynamo_exp_tpu.protocols.common import BackendInput

        for _ in range(3):
            ctx = AsyncEngineContext()
            ctx.start_timeout(0.05)  # expires during the transfer wait
            b = BackendInput(token_ids=list(range(3, 23)))
            stream = await engine.generate(b.to_dict(), ctx)
            out = (await collect(stream))[0]
            assert out["remote"] is False  # fell back locally
            # Drain the unserviced item so the queue-depth gate doesn't
            # veto the next remote attempt.
            assert await inner_queue.pull(timeout_s=0.5) is not None
        assert engine.local_fallbacks == 3
        assert engine.breaker.state is BreakerState.CLOSED
        assert engine.breaker.consecutive_failures == 0
    finally:
        await recv.close()


async def test_half_open_probe_released_on_deadline_expiry_in_decode():
    """Regression (ROADMAP open item): a HALF_OPEN probe claimed by the
    remote-prefill path whose wait is cut short by the *request's own
    deadline* records neither success nor failure — it must RELEASE the
    probe slot, or the breaker sticks in HALF_OPEN and remote prefill is
    locked out forever. The breaker must then exit HALF_OPEN via the
    next (successful) probe."""
    from dynamo_exp_tpu.protocols.common import BackendInput

    sched = ChaosSchedule(SEEDS[0])
    t = [0.0]
    breaker = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=lambda: t[0])
    engine, inner_queue, recv = make_disagg(
        sched, transfer_timeout_s=60.0, breaker=breaker
    )
    await recv.start()
    try:
        breaker.record_failure()
        assert breaker.state is BreakerState.OPEN
        t[0] = 6.0  # cooldown over: next dispatch becomes the probe

        ctx = AsyncEngineContext()
        ctx.start_timeout(0.05)  # expires during the transfer wait
        b = BackendInput(token_ids=list(range(3, 23)))
        out = (await collect(await engine.generate(b.to_dict(), ctx)))[0]
        assert out["remote"] is False  # fell back locally
        assert breaker.state is BreakerState.HALF_OPEN
        # THE regression: without release(), the probe slot stays claimed
        # and no request may ever probe again.
        assert breaker.would_allow()
        assert await inner_queue.pull(timeout_s=0.5) is not None

        # And the breaker exits HALF_OPEN on the next, successful probe.
        service = asyncio.ensure_future(
            fake_prefill_service(inner_queue, engine.engine.cfg)
        )
        out = await run_one(engine)
        await asyncio.wait_for(service, 5)
        assert out["remote"] is True
        assert breaker.state is BreakerState.CLOSED
    finally:
        await recv.close()


async def test_half_open_probe_released_on_cancelled_dispatch():
    """Regression (ROADMAP open item): a CancelledError escaping between
    ``health.acquire()`` and any outcome in the push router leaked the
    half-open probe slot (the ConnectionError-only handler never saw
    it). The slot must be released outcome-free and the breaker must
    exit HALF_OPEN via the next probe."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    hang = asyncio.Event()
    served_after_hang: list = []

    async def handler(request, context=None):
        if not hang.is_set():
            await asyncio.Event().wait()  # hang forever (first probe)
        served_after_hang.append(1)
        yield Annotated.from_data({"tok": 1}).to_dict()

    a = await ep.serve_endpoint(handler)
    t = [0.0]
    health = HealthTracker(failure_threshold=1, cooldown_s=5.0, clock=lambda: t[0])
    client = await ep.client(health=health)
    await client.wait_for_instances(1, timeout=2)
    router = fast_router(client, retries=0)

    health.record_failure(a.instance_id)
    breaker = health.breaker(a.instance_id)
    assert breaker.state is BreakerState.OPEN
    t[0] = 6.0  # cooldown over: the next dispatch claims the probe

    task = asyncio.ensure_future(router.generate({}))
    await asyncio.sleep(0.05)  # parked inside open_stream's first-frame pull
    assert breaker.state is BreakerState.HALF_OPEN
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    # THE regression: the cancelled dispatch must free the probe slot.
    assert breaker.state is BreakerState.HALF_OPEN
    assert breaker.would_allow()

    # Breaker exits HALF_OPEN: the next probe succeeds and closes it.
    hang.set()
    out = await collect(await router.generate({}))
    assert [o["tok"] for o in out] == [1]
    assert breaker.state is BreakerState.CLOSED
    assert served_after_hang == [1]
    await drt.close()


async def test_queue_size_outage_means_prefill_locally():
    """Satellite: a broken queue.size() must not crash the request — the
    decision degrades to local prefill (best-effort contract)."""
    sched = ChaosSchedule(SEEDS[0])
    engine, _, recv = make_disagg(sched)
    await recv.start()
    try:
        sched.fail_queue("size", times=-1)
        out = await run_one(engine)
        assert out["remote"] is False
        assert engine.queue_probe_failures == 1
        # The failed probe never reached _remote_prefill: no fallback tick.
        assert engine.local_fallbacks == 0
    finally:
        await recv.close()


# ------------------------------------------------------------------ deadlines
async def test_expired_deadline_stops_at_router_before_dispatch():
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    _, a, b, client = await serve_two_workers(drt, calls)
    router = fast_router(client)
    ctx = AsyncEngineContext()
    ctx.start_timeout(0.0)
    with pytest.raises(DeadlineExceededError, match="router"):
        await router.generate({}, ctx)
    assert calls == [] and sched.injected == []
    await drt.close()


async def test_expired_deadline_refused_by_request_plane():
    """Bypassing the router's check, the plane itself refuses in-band."""
    drt = DistributedRuntime.detached()
    calls: list = []
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    await ep.serve_endpoint(make_worker("a", calls))
    client = await ep.client()
    await client.wait_for_instances(1, timeout=2)
    ctx = AsyncEngineContext()
    ctx.deadline = time.time() - 1
    frames = await client.generate_to(client.instances[0], {}, ctx)
    with pytest.raises(EngineError, match="deadline exceeded"):
        await collect(frames)
    assert calls == []
    await drt.close()


async def test_expired_deadline_refused_by_tcp_plane():
    """Over real TCP the remaining budget rides the request header and
    the server refuses before invoking the handler."""
    from dynamo_exp_tpu.runtime.transports.tcp import TcpRequestPlane
    from dynamo_exp_tpu.runtime.transports.base import EndpointAddress, InstanceInfo

    plane = TcpRequestPlane()
    calls: list = []
    info = InstanceInfo(
        address=EndpointAddress("ft", "worker", "generate"), instance_id=77
    )
    served = await plane.serve(info, make_worker("a", calls))
    client = Client.new_static(plane, [info])
    try:
        ctx = AsyncEngineContext()
        ctx.deadline = time.time()  # zero remaining budget
        frames = await client.generate_to(info, {}, ctx)
        with pytest.raises(EngineError, match="deadline exceeded"):
            await collect(frames)
        assert calls == []
        # Sanity: an unexpired context on the same plane flows normally.
        ok = await client.generate_to(info, {}, AsyncEngineContext())
        assert len(await collect(ok)) == 3
    finally:
        await served.close()
        await plane.close()


async def test_expired_deadline_cancels_queued_prefill_before_transfer():
    """Acceptance: the prefill worker drops an expired queue item without
    prefill compute or KV transfer."""
    from dynamo_exp_tpu.disagg import PrefillWorker, RemotePrefillRequest

    class NeverPrefillEngine:
        prefill_calls = 0

        async def prefill_extract(self, binput):
            NeverPrefillEngine.prefill_calls += 1
            raise AssertionError("expired request must not be prefilled")

    queue = InProcWorkQueue()
    worker = PrefillWorker(NeverPrefillEngine(), queue)
    req = RemotePrefillRequest(
        request_id="expired-1",
        token_ids=[1, 2, 3],
        return_addr="127.0.0.1:1",  # nothing listens: a send would fail loudly
        deadline_unix=time.time() - 0.5,
    )
    await worker._serve_one(req.to_bytes())
    assert worker.expired == 1
    assert worker.served == 0 and worker.failed == 0
    assert NeverPrefillEngine.prefill_calls == 0

    # A live deadline still gets served (engine raising marks it failed,
    # proving the worker got past the deadline gate).
    live = RemotePrefillRequest(
        request_id="live-1",
        token_ids=[1, 2, 3],
        return_addr="127.0.0.1:1",
        deadline_unix=time.time() + 60,
    )
    await worker._serve_one(live.to_bytes())
    assert NeverPrefillEngine.prefill_calls == 1
    assert worker.failed == 1 and worker.expired == 1


# -------------------------------------------------------------- graceful drain
async def test_drain_removes_instance_with_zero_failed_inflight():
    """Acceptance: drain intent (the ``llmctl drain`` KV key) flips the
    instance to draining, routers stop sending it new work, and the
    in-flight stream finishes cleanly."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    _, a, b, client = await serve_two_workers(drt, calls, step_delay_s=0.02)
    router = fast_router(client)

    # Round-robin: first request lands on a and streams slowly.
    inflight = asyncio.ensure_future(collect(await router.generate({})))
    await asyncio.sleep(0.01)
    assert calls == ["a"]

    # Operator drains a (the exact write `llmctl drain <id>` performs).
    await drt.discovery.kv_put(f"{DRAIN_PREFIX}{a.instance_id}", b"1")
    for _ in range(200):
        live = {i.instance_id: i for i in client.instances}
        if live.get(a.instance_id) and live[a.instance_id].metadata.get("draining"):
            break
        await asyncio.sleep(0.005)
    else:
        pytest.fail("drain metadata never reached the client")
    assert a.is_draining

    # New work only reaches b.
    for _ in range(4):
        out = await collect(await router.generate({}))
        assert {o["worker"] for o in out} == {"b"}

    # The in-flight request on a finished untouched: zero failures.
    out = await asyncio.wait_for(inflight, 5)
    assert [o["tok"] for o in out] == [1, 2, 3]
    assert {o["worker"] for o in out} == {"a"}

    # close() completes the drain (deregister + wait for inflight=0).
    await a.close()
    for _ in range(200):
        if all(i.instance_id != a.instance_id for i in client.instances):
            break
        await asyncio.sleep(0.005)
    assert all(i.instance_id != a.instance_id for i in client.instances)
    await drt.close()


async def test_llmctl_drain_command_drives_worker_drain():
    """The llmctl subcommand itself: validates liveness, writes the
    drain key, and the worker's drain watcher picks it up."""
    import argparse

    from dynamo_exp_tpu.llmctl import drain_instance

    drt = DistributedRuntime.detached()
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    a = await ep.serve_endpoint(make_worker("a", []))

    # Unknown instance: refused, nothing written.
    rc = await drain_instance(drt, argparse.Namespace(instance_id=999999))
    assert rc == 1
    assert await drt.discovery.kv_get(f"{DRAIN_PREFIX}999999") is None

    rc = await drain_instance(drt, argparse.Namespace(instance_id=a.instance_id))
    assert rc == 0
    for _ in range(200):
        if a.is_draining:
            break
        await asyncio.sleep(0.005)
    assert a.is_draining
    # The worker consumes its drain key — intents must not pile up.
    for _ in range(200):
        key = f"{DRAIN_PREFIX}{a.instance_id}"
        if await drt.discovery.kv_get(key) is None:
            break
        await asyncio.sleep(0.005)
    assert await drt.discovery.kv_get(f"{DRAIN_PREFIX}{a.instance_id}") is None
    await drt.close()


async def test_drained_singleton_yields_503_shaped_error():
    """All instances draining → NoHealthyInstancesError (the HTTP 503 +
    Retry-After path), not a confusing connection error."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    a = await ep.serve_endpoint(make_worker("a", []))
    client = await ep.client()
    await client.wait_for_instances(1, timeout=2)
    router = fast_router(client)
    await a.drain()
    for _ in range(200):
        if client.instances and client.instances[0].metadata.get("draining"):
            break
        await asyncio.sleep(0.005)
    with pytest.raises(NoHealthyInstancesError):
        await router.generate({})
    await drt.close()


# -------------------------------------------------------------- discovery flap
async def test_client_watch_resubscribes_after_discovery_flap():
    """Satellite: a dying watch stream must not freeze the client's
    membership view — it logs, re-subscribes, and re-lists."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    ep = drt.namespace("ft").component("worker").endpoint("generate")
    await ep.serve_endpoint(
        make_worker("a", []), lease=await drt.discovery.create_lease()
    )
    client = await ep.client()
    await client.wait_for_instances(1, timeout=2)

    # Break the next two watch pushes; registrations during the gap are
    # only recoverable via the on-resume re-list.
    sched.fail_watch(times=2)
    await ep.serve_endpoint(
        make_worker("b", []), lease=await drt.discovery.create_lease()
    )
    await client.wait_for_instances(2, timeout=5)
    assert len(client.instances) == 2

    # The repaired watch keeps tracking future changes too.
    await ep.serve_endpoint(
        make_worker("c", []), lease=await drt.discovery.create_lease()
    )
    await client.wait_for_instances(3, timeout=5)
    assert len(client.instances) == 3
    await drt.close()


# --------------------------------------------------------------- determinism
async def _failover_scenario(seed: int):
    """A fixed chaotic workload; returns (results, normalized fault log)."""
    sched = ChaosSchedule(seed)
    drt = chaos_runtime(sched)
    calls: list = []
    _, a, b, client = await serve_two_workers(drt, calls)
    router = fast_router(client, seed)
    sched.fail_requests(instance_id=a.instance_id, times=1)
    sched.delay_requests(0.002, times=2)
    results = []
    for _ in range(4):
        out = await collect(await router.generate({}))
        results.append([o["worker"] for o in out])
    # Instance ids are globally monotonic across runs; normalize by
    # order of appearance so two runs are comparable.
    ids = {}
    norm = []
    for entry in sched.injected:
        op, iid, kind = entry.split(":")
        norm.append((op, ids.setdefault(iid, len(ids)), kind))
    await drt.close()
    return results, norm, list(calls)


@pytest.mark.parametrize("seed", SEEDS)
async def test_chaos_schedule_is_deterministic_across_runs(seed):
    """Acceptance: same seed + same script + same workload → identical
    results and identical injected-fault sequence, run twice."""
    first = await _failover_scenario(seed)
    second = await _failover_scenario(seed)
    assert first == second
