"""AOT variant precompilation + warm-boot provisioning (docs/aot.md).

Four layers of proof, all on the CPU mesh:

1. **Manifest determinism** — same config → byte-identical manifest
   JSON and hash, in-process and across processes (the hash IS the
   cache-invalidation key); lattice enumeration covers every value the
   live ``*_bucket_for`` helpers can emit.
2. **Warm boot** — a second engine booted from a populated persistent
   compilation cache compiles ZERO new variants: no ragged compile
   misses under traffic, no variant growth past the prewarmed set, no
   new cache entries — and the profiler's freshness state is seeded so
   prewarmed move kernels are never mis-charged as cold compiles.
3. **Identity** — greedy / seeded / penalized / spec-on streams are
   token-identical between a prewarmed engine and a cold one (prewarm
   executes all-padding batches; nothing it computes can reach an
   emitted token).
4. **The provisioning study** — feeding ``plan_step_slo`` the warm
   ``provision_s`` absorbs the same diurnal burst with fewer
   chip-seconds AND better SLO attainment than the cold one, and
   ``sim/fit.py`` learns warm-vs-cold ``provision_s`` from tagged
   coldstart bench lines.
"""

import asyncio
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dynamo_exp_tpu.aot import (
    build_manifest,
    manifest_for_engine,
    mixed_token_buckets,
    page_bound_buckets,
    page_move_buckets,
    resolve_ragged_key,
    windowed_token_buckets,
)
from dynamo_exp_tpu.aot.lattice import CompileManifest
from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput, SamplingOptions
from dynamo_exp_tpu.telemetry.dispatch import DispatchProfiler

PS = 8


def _cfg(**over) -> EngineConfig:
    base = dict(
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=64,
        max_model_len=128,
        prefill_chunk=16,
        decode_window=4,
        eos_token_ids=[],
        kv_dtype="float32",
    )
    return EngineConfig(**(base | over))


def _engine(**over) -> TPUEngine:
    return TPUEngine(_cfg(**over), mesh=single_device_mesh(), seed=0)


async def _collect(engine, prompt, max_tokens=8, seed=None, **sampling):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = True
    if sampling or seed is not None:
        b.sampling_options = SamplingOptions(seed=seed, **sampling)
    stream = await engine.generate(b.to_dict())
    toks = []
    async for item in stream:
        toks.extend(item.get("token_ids", []))
    return toks


# ------------------------------------------------------------ determinism
def _manifest(cfg=None) -> CompileManifest:
    return build_manifest(
        cfg or _cfg(),
        attn_impl="xla",
        mesh_shape={"tp": 1, "sp": 1},
        jax_version="test",
    )


def test_manifest_hash_deterministic_in_process():
    a, b = _manifest(), _manifest()
    assert a.to_json() == b.to_json()
    assert a.hash() == b.hash()
    # JSON round-trip preserves the hash (what `llmctl aot list` /
    # warm-boot hash checks compare).
    assert CompileManifest.from_json(a.to_json()).hash() == a.hash()


def test_manifest_hash_moves_with_lattice_inputs():
    base = _manifest().hash()
    assert _manifest(_cfg(max_decode_slots=4)).hash() != base
    assert _manifest(_cfg(num_pages=128)).hash() != base
    assert _manifest(_cfg(decode_window=8)).hash() != base
    assert _manifest(_cfg(spec_mode="ngram")).hash() != base


def test_manifest_hash_identical_across_processes(tmp_path):
    """The acceptance bit: same config → byte-identical hash in a
    DIFFERENT interpreter (no id()/hash()/dict-order leakage)."""
    script = (
        "import json\n"
        "from dynamo_exp_tpu.aot import build_manifest\n"
        "from dynamo_exp_tpu.engine import EngineConfig\n"
        "from dynamo_exp_tpu.models import TINY\n"
        "cfg = EngineConfig(model=TINY, max_decode_slots=2, page_size=8,\n"
        "                   num_pages=64, max_model_len=128,\n"
        "                   prefill_chunk=16, decode_window=4,\n"
        "                   eos_token_ids=[], kv_dtype='float32')\n"
        "m = build_manifest(cfg, attn_impl='xla',\n"
        "                   mesh_shape={'tp': 1, 'sp': 1},\n"
        "                   jax_version='test')\n"
        "print(m.hash())\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        check=True,
        env=os.environ | {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "42"},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.stdout.decode().strip().splitlines()[-1] == _manifest().hash()


def test_bucket_enumeration_covers_live_helpers():
    """Every value a ``*_bucket_for`` helper can return for a legal
    input appears in the enumerated bucket set — the lattice has no
    blind spots the live loop could dispatch into."""
    cfg = _cfg(max_decode_slots=8, max_model_len=256)
    wb = set(windowed_token_buckets(cfg))
    for n in range(1, cfg.max_decode_slots + 1):
        assert cfg.ragged_tokens_bucket_for(n) in wb
    mb = set(mixed_token_buckets(cfg))
    for n in range(1, cfg.ragged_max_tokens + 1, 7):
        assert cfg.ragged_tokens_bucket_for(n, mixed=True) in mb
    pb = set(page_bound_buckets(cfg))
    for p in range(1, cfg.max_pages_per_seq + 1):
        assert cfg.ragged_page_bucket_for(p) in pb
    # Move buckets must cover cross-sequence eviction bursts too: one
    # _flush_offloads sweep can gather up to the whole pool, not just
    # one sequence's pages.
    mv = set(page_move_buckets(cfg))
    for p in range(1, max(cfg.num_pages, cfg.max_pages_per_seq) + 1, 3):
        assert cfg.page_move_bucket_for(p) in mv


def test_resolved_key_matches_live_ragged_fn():
    """The engine's ``_ragged_fn`` and the offline ``resolve_ragged_key``
    are literally the same keying rule (one computes through the
    other) — a dispatch lands in ``_ragged_fns`` under the key the
    lattice predicts."""
    eng = _engine()
    key = resolve_ragged_key(
        eng.cfg, eng._attn_impl, 2, 4, True, False, False
    )
    eng._ragged_fn(2, 4, True, False, False)
    assert key in eng._ragged_fns


# --------------------------------------------------------------- warm boot
def test_warm_boot_compiles_nothing(tmp_path):
    """Two boots against one persistent cache dir: the first populates
    (prewarm executes + serializes every variant), the second
    deserializes — zero ragged compile misses under traffic, zero
    variant growth past the prewarmed set, zero new cache entries, and
    the move-kernel freshness state seeded (satellite: prewarm must
    never be mis-charged as a cold compile)."""
    cache = str(tmp_path / "cache")

    def boot():
        eng = _engine()
        manifest = manifest_for_engine(eng)
        report = eng.prewarm(manifest, cache_dir=cache)
        toks_g = asyncio.run(_collect(eng, range(20, 36)))
        toks_s = asyncio.run(
            _collect(eng, range(20, 36), seed=5, temperature=0.8)
        )
        m = eng.metrics()
        eng.stop()
        return eng, manifest, report, m, (toks_g, toks_s)

    eng1, manifest, rep1, m1, toks1 = boot()
    assert rep1.ragged_variants == len(manifest.ragged)
    assert m1["prewarmed_variants"] == rep1.variants > 0
    assert m1["prewarm_seconds"] > 0
    files1 = len(os.listdir(cache))
    assert files1 > 0, "persistent cache serialized nothing"

    eng2, _, rep2, m2, toks2 = boot()
    # Zero compiles on second boot's traffic: the misses counter stays
    # flat from the very first dispatch...
    assert m2["dispatch"]["ragged"]["compile_misses"] == 0
    assert m2["dispatch"]["ragged"]["compile_total_s"] == 0.0
    # ...traffic never grows the cache past the prewarmed lattice...
    assert m2["compiled_ragged_variants"] == len(manifest.ragged)
    assert m2["compiled_ragged_variants"] == m1["compiled_ragged_variants"]
    # ...and the persistent cache gained nothing (every executable the
    # second boot needed was already serialized).
    assert len(os.listdir(cache)) == files1
    # Prewarm seeded the move-kernel freshness state: a prewarmed
    # bucket's first live dispatch must not read as a fresh compile.
    for bucket in manifest.move_buckets:
        assert not eng2.profiler.first_variant("gather", bucket)
        assert not eng2.profiler.first_variant("scatter", bucket)
    assert not eng2.profiler.first_variant("cow", 0)
    # Same streams both boots (and prewarm left no residue).
    assert toks1 == toks2


def test_prewarm_refuses_running_engine():
    eng = _engine()
    eng.start()
    try:
        with pytest.raises(RuntimeError, match="before the engine"):
            eng.prewarm()
    finally:
        eng.stop()


def test_profiler_seed_variants_suppresses_first_variant():
    prof = DispatchProfiler()
    prof.seed_variants("gather", (8, 16))
    assert not prof.first_variant("gather", 8)
    assert not prof.first_variant("gather", 16)
    assert prof.first_variant("gather", 32)  # unseeded keys still fresh


# ---------------------------------------------------------------- identity
def test_identity_prewarmed_vs_cold_all_sampler_modes():
    """Greedy / seeded / penalized / spec-on streams are token-identical
    between a prewarmed engine and a cold one: prewarm's all-padding
    batches write no KV and touch no live penalty row, so the first
    real request sees exactly a cold engine's state."""
    over = dict(spec_mode="ngram", spec_draft_len=3, spec_adaptive=False)
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(3, 200, size=8 + 3 * i)) for i in range(3)]
    block = [50, 51, 52, 53, 54, 55, 56, 57]
    reqs = [
        (prompts[0], {}),
        (prompts[1], {"seed": 7, "temperature": 0.8, "top_k": 20}),
        (
            prompts[2],
            {
                "seed": 11,
                "temperature": 0.7,
                "frequency_penalty": 0.4,
                "presence_penalty": 0.2,
                "repetition_penalty": 1.2,
            },
        ),
        (block * 4, {}),  # repetitive: the n-gram drafter engages
    ]

    def streams(prewarmed: bool):
        eng = _engine(**over)
        if prewarmed:
            eng.prewarm()
        out = [
            asyncio.run(_collect(eng, p, 10, **kw)) for p, kw in reqs
        ]
        spec = eng.spec_dispatches
        eng.stop()
        return out, spec

    warm, warm_spec = streams(True)
    cold, cold_spec = streams(False)
    assert warm == cold
    assert warm_spec > 0 and cold_spec > 0  # speculation actually ran


# --------------------------------------------------- provisioning study
@pytest.mark.sim
def test_diurnal_burst_warm_provision_fewer_chip_seconds():
    """The ROADMAP acceptance: with the measured warm ``provision_s``,
    ``plan_step_slo`` absorbs the same diurnal burst with FEWER
    chip-seconds than the cold baseline while meeting the SLOs at
    least as well — scale-up lands on the burst's rising edge instead
    of being bought in advance as standby capacity."""
    from dynamo_exp_tpu.planner import PlannerConfig, SloTargets
    from dynamo_exp_tpu.sim import (
        ClusterSim,
        ServiceTimeModel,
        SimConfig,
        diurnal_workload,
    )

    def run(provision_s: float):
        workload = diurnal_workload(
            7, duration_s=900.0, rps_base=0.5, rps_peak=12.0,
            period_s=300.0,
        )
        cfg = SimConfig(
            seed=7,
            slots_per_instance=8,
            pages_per_instance=256,
            page_size=16,
            max_inflight=64,
            admission_per_instance=True,
            initial_instances=1,
            provision_s=provision_s,
            planner="slo",
            planner_cfg=PlannerConfig(max_tpu_budget=16, min_endpoint=1),
            slo=SloTargets(
                ttft_p99_slo_s=2.0,
                itl_p99_slo_s=0.2,
                provision_s=provision_s,
            ),
            service=ServiceTimeModel.default(),
            record_events=False,
        )
        return ClusterSim(cfg, workload).run()

    cold = run(120.0)  # cold boot: first traffic pays the lattice
    warm = run(8.0)  # warm boot from a populated compile cache
    assert warm.chip_seconds < cold.chip_seconds, (
        warm.chip_seconds, cold.chip_seconds,
    )
    assert warm.goodput_requests >= cold.goodput_requests
    assert warm.slo_violations_ttft <= cold.slo_violations_ttft
    # Deterministic per seed (the sim suite's standing rule).
    again = run(8.0)
    assert again.chip_seconds == warm.chip_seconds
    assert again.goodput_requests == warm.goodput_requests


def test_fit_learns_warm_provision_from_tagged_bench_lines(tmp_path):
    """``sim/fit.py`` splits coldstart samples by their ``prewarmed``
    tag: warm samples win (the fleet plans with its warm landing
    delay); cold-only files fall back to the cold samples."""
    from dynamo_exp_tpu.sim.fit import ServiceTimeModel

    def line(arm, prov, prewarmed):
        return {
            "metric": f"coldstart_tiny_isl64_osl16_c2_{arm}",
            "value": prov,
            "provision_s": prov,
            "prewarmed": prewarmed,
            "manifest_hash": "abc",
        }

    both = tmp_path / "bench.json"
    both.write_text(
        json.dumps(line("cold", 120.0, False))
        + "\n"
        + json.dumps(line("warm", 8.0, True))
        + "\n"
    )
    model = ServiceTimeModel.from_bench_json([both])
    assert model.provision_s == 8.0
    assert model.planner_hints()["provision_s"] == 8.0

    cold_only = tmp_path / "cold.json"
    cold_only.write_text(json.dumps(line("cold", 120.0, False)) + "\n")
    assert ServiceTimeModel.from_bench_json([cold_only]).provision_s == 120.0


# --------------------------------------------------------------------- CLI
def test_llmctl_aot_list_prints_manifest(capsys):
    from dynamo_exp_tpu.llmctl import main as llmctl_main

    rc = llmctl_main(
        [
            "aot", "list", "--preset", "tiny", "--max-decode-slots", "2",
            "--page-size", "8", "--max-model-len", "128",
            "--prefill-chunk", "16", "--kv-dtype", "float32",
        ]
    )
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    manifest = CompileManifest.from_dict(doc)
    assert manifest.ragged and manifest.move_buckets
    assert manifest.engine["max_decode_slots"] == 2
