"""dynlint runner: walk the tree, run the checkers, report findings.

Entry points: ``llmctl lint`` (dynamo_exp_tpu/llmctl.py), ``python -m
dynamo_exp_tpu.analysis`` (pure stdlib — usable in a bare CI job), the
``make lint`` target, and the tier-1 gate in tests/test_analysis.py
(zero unwaived findings on the full tree).

``--baseline`` exists for incremental adoption during large refactors
(the ragged-kernel rewrite): ``--update-baseline`` snapshots today's
unwaived findings (line-number-free fingerprints), and subsequent runs
with ``--baseline`` report only *new* ones — the floor can only
ratchet down.
"""

from __future__ import annotations

import ast
import json
import os
import sys

from .core import Finding, apply_waivers, parse_waivers, statement_spans
from .determinism import DeterminismChecker
from .host_sync import HostSyncChecker
from .ownership import ThreadOwnershipChecker
from .recompile import RecompileHazardChecker

# Rule name -> one-line description. The doc-sync test walks this
# registry: every name must appear in docs/static_analysis.md (same
# discipline as the metrics doc-sync in tests/test_telemetry.py).
RULES: dict[str, str] = {
    "host-sync": (
        "no implicit device→host syncs in hot-path zones outside the "
        "inline-waived allowlist"
    ),
    "determinism": (
        "no wall clocks / unseeded RNGs / run-global ids in "
        "seed-deterministic zones or flight-recorder payloads"
    ),
    "thread-ownership": (
        "no mutation of engine-loop-owned state from non-loop call "
        "paths; lock-guarded state only under its lock"
    ),
    "recompile-hazard": (
        "compiled-variant cache keys must derive from *_bucket_for "
        "helpers, never raw dynamic ints"
    ),
    "waiver-syntax": (
        "every # dynlint: waiver needs a known token and a non-empty "
        "reason"
    ),
}

# Inline waiver token -> the rule it waives.
WAIVER_TOKENS: dict[str, str] = {
    "sync-point": "host-sync",
    "determinism": "determinism",
    "thread-ownership": "thread-ownership",
    "recompile-hazard": "recompile-hazard",
}

_SKIP_DIRS = {"__pycache__", ".git"}


def default_root() -> str:
    """The repo root (parent of the installed package directory)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def iter_source_files(root: str) -> list[str]:
    """Repo-relative posix paths of every package .py file under
    ``<root>/dynamo_exp_tpu``. tests/, bench.py and examples/ are not
    zone members; scanning only the package keeps fixtures and harness
    code out of the gate."""
    pkg_dir = os.path.join(root, "dynamo_exp_tpu")
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                full = os.path.join(dirpath, fname)
                out.append(os.path.relpath(full, root).replace(os.sep, "/"))
    return sorted(out)


def default_checkers() -> list:
    return [
        HostSyncChecker(),
        DeterminismChecker(),
        ThreadOwnershipChecker(),
        RecompileHazardChecker(),
    ]


def lint_tree(
    root: str | None = None,
    rules: list[str] | None = None,
    files: list[str] | None = None,
    checkers: list | None = None,
) -> list[Finding]:
    """Run the suite over the tree; returns ALL findings (waived ones
    marked, so callers can audit the allowlist too). ``rules`` filters
    the reported rule set (``waiver-syntax`` always runs: a broken
    waiver must never silently pass a filtered run)."""
    root = root or default_root()
    if files is None:
        files = iter_source_files(root)
    else:
        # Normalize operator-supplied paths (absolute, ./-prefixed, OS
        # separators) to the repo-relative posix form zones and
        # manifests are declared in — otherwise every checker silently
        # skips the file and its waivers all look stale.
        files = [
            os.path.relpath(
                p if os.path.isabs(p) else os.path.join(root, p), root
            ).replace(os.sep, "/")
            for p in files
        ]
    checkers = checkers if checkers is not None else default_checkers()
    findings: list[Finding] = []
    for rel in files:
        path = os.path.join(root, rel)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel)
        except (OSError, SyntaxError) as e:
            findings.append(
                Finding(
                    rule="waiver-syntax",
                    file=rel,
                    line=getattr(e, "lineno", 1) or 1,
                    col=0,
                    message=f"unparseable file: {e}",
                )
            )
            continue
        waivers, waiver_findings = parse_waivers(rel, source, WAIVER_TOKENS)
        file_findings: list[Finding] = []
        for checker in checkers:
            if rules and checker.rule not in rules:
                continue
            file_findings.extend(checker.check(rel, tree, source))
        consumed = apply_waivers(
            file_findings, waivers, statement_spans(tree)
        )
        if not rules:
            # Stale-waiver guard (full runs only — under --rule a
            # waiver for an unselected rule is legitimately unmatched):
            # a waiver that no longer covers any finding means the
            # allowlist has drifted from the code.
            for line, by_rule in waivers.items():
                for rule in by_rule:
                    if (line, rule) not in consumed:
                        waiver_findings.append(
                            Finding(
                                rule="waiver-syntax",
                                file=rel,
                                line=line,
                                col=0,
                                message=(
                                    f"unused waiver: no {rule} finding "
                                    f"on this statement — remove the "
                                    f"stale # dynlint comment"
                                ),
                            )
                        )
        findings.extend(file_findings)
        findings.extend(waiver_findings)
    if rules:
        findings = [
            f for f in findings if f.rule in rules or f.rule == "waiver-syntax"
        ]
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# ------------------------------------------------------------- baselines
def load_baseline(path: str) -> set[str]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return set(data.get("fingerprints", []))


def save_baseline(path: str, fingerprints: list[str]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {"fingerprints": sorted(set(fingerprints))}, f, indent=2
        )
        f.write("\n")


def _fingerprints(root: str, findings: list[Finding]) -> dict[int, str]:
    """id(finding) -> fingerprint (reads each file once). Textually
    identical findings get an ordinal suffix (#0, #1, …) in report
    order, so the baseline is a *multiset*: baselining one occurrence
    of a line cannot suppress a second, NEW occurrence of the same
    text elsewhere in the file."""
    lines_by_file: dict[str, list[str]] = {}
    seen: dict[str, int] = {}
    out: dict[int, str] = {}
    for f in findings:
        if f.file not in lines_by_file:
            try:
                with open(
                    os.path.join(root, f.file), encoding="utf-8"
                ) as fh:
                    lines_by_file[f.file] = fh.read().splitlines()
            except OSError:
                lines_by_file[f.file] = []
        base = f.fingerprint(lines_by_file[f.file])
        n = seen.get(base, 0)
        seen[base] = n + 1
        out[id(f)] = f"{base}#{n}"
    return out


# ------------------------------------------------------------------- CLI
def add_lint_args(parser) -> None:
    parser.add_argument(
        "paths", nargs="*",
        help="repo-relative files to lint (default: the whole package)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument(
        "--rule", action="append", default=None, choices=sorted(RULES),
        help="run only this rule (repeatable); waiver-syntax always runs",
    )
    parser.add_argument(
        "--root", default=None, help="repo root (default: auto-detected)"
    )
    parser.add_argument(
        "--baseline", default=None,
        help="suppress findings fingerprinted in this baseline file",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="write the current unwaived findings to --baseline and exit 0",
    )
    parser.add_argument(
        "--show-waived", action="store_true",
        help="also print the waived (allowlisted) findings",
    )


def run_cli(args) -> int:
    root = args.root or default_root()
    if args.update_baseline and not args.baseline:
        print(
            "--update-baseline requires --baseline <file> to write to",
            file=sys.stderr,
        )
        return 2
    files = list(args.paths) or None
    findings = lint_tree(root, rules=args.rule, files=files)
    unwaived = [f for f in findings if not f.waived]
    if args.baseline:
        fps = _fingerprints(root, unwaived)
        if args.update_baseline:
            save_baseline(args.baseline, [fps[id(f)] for f in unwaived])
            print(
                f"baseline: {len(unwaived)} finding(s) -> {args.baseline}",
                file=sys.stderr,
            )
            return 0
        if os.path.exists(args.baseline):
            known = load_baseline(args.baseline)
            unwaived = [f for f in unwaived if fps[id(f)] not in known]
    waived = [f for f in findings if f.waived]
    if args.as_json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in unwaived],
                    "waived": [f.to_dict() for f in waived],
                    "counts": {
                        "unwaived": len(unwaived),
                        "waived": len(waived),
                    },
                },
                indent=2,
            )
        )
    else:
        shown = unwaived + (waived if args.show_waived else [])
        shown.sort(key=lambda f: (f.file, f.line, f.rule))
        for f in shown:
            tag = f" [waived: {f.reason}]" if f.waived else ""
            print(
                f"{f.file}:{f.line}:{f.col}: {f.rule}: {f.message}{tag}"
            )
        print(
            f"dynlint: {len(unwaived)} unwaived finding(s), "
            f"{len(waived)} waived (allowlisted)",
            file=sys.stderr,
        )
    return 1 if unwaived else 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="dynlint",
        description="AST invariant checks (docs/static_analysis.md)",
    )
    add_lint_args(parser)
    return run_cli(parser.parse_args(argv))
