# Convenience targets mirroring the CI tiers (.github/workflows/).
# CPU-only: everything runs on the virtual 8-device CPU mesh.

PYTEST := env JAX_PLATFORMS=cpu python -m pytest
# Three fixed seeds for the deterministic fault-injection suite; each
# run must inject the same faults at the same points (the suite itself
# asserts cross-run determinism per seed).
CHAOS_SEED_SETS := 7,21,1337 11,23,4242 1,2,3
# Recovery seed set: the mid-stream-failover (resumable streams) suite
# sweeps crash-at-token faults under these seeds pre-merge.
RECOVERY_SEED_SETS := 7,21,1337 5,8,13
# Overload seed sets: seeded overload_burst scenarios (mixed-priority
# bursts against a tiny KV pool) driving edge shedding + KV-pressure
# preemption in tests/test_overload.py.
OVERLOAD_SEED_SETS := 7,21,1337 3,9,27
# Simulation seed sets: the discrete-event cluster simulator's
# regression runs (determinism, calibration vs the live overload
# harness, reactive-vs-SLO planner comparison) in tests/test_sim.py.
SIM_SEED_SETS := 7,21,1337 3,9,27
# Speculative-decoding seed set: re-run the resumable (mid-stream
# failover) and overload (preempt→resume) identity suites with
# speculation force-enabled via the DYN_SPEC env toggle — every stream
# must stay token-identical with spec on (docs/speculative.md).
SPEC_SEED_SETS := 7,21,1337
# Predictive KV tiering seed sets: the 8x-pool aggregate-context
# identity sweep (proactive offload + prefetch under pressure,
# conservation-audited) in tests/test_kv_tiering.py.
TIERING_SEED_SETS := 7,21,1337 3,9,27
# Spot-reclamation seed sets: deadline-bounded live migration +
# journal failover (tests/test_reclaim.py) — migrated streams must be
# token-identical to uninterrupted oracles, and a too-short grace must
# degrade to journal failover with zero lost/duplicated tokens.
RECLAIM_SEED_SETS := 7,21,1337 5,8,13
# Durable-KV storage-fault seed sets: the seeded storage chaos family
# (bit-flip, torn tail, ENOSPC, injected fetch latency, store-dir
# missing) against the G3 persistent tier (tests/test_kv_persist.py)
# — corrupt pages must quarantine with token-identical journal
# re-prefill, and a failing store must degrade to G2-only, never hang.
STORE_SEED_SETS := 7,21,1337 3,9,27

.PHONY: test pre-merge nightly chaos sim sim-scale flight profile-smoke lint prewarm-smoke bench-compare anatomy-smoke tune-smoke

test:
	$(PYTEST) tests/ -q -m "not tpu and not weekly"

pre-merge:
	$(PYTEST) tests/ -q -m pre_merge

nightly:
	$(PYTEST) tests/ -q -m "not tpu and not weekly"

# Fault-injection suite under three fixed seed sets (satellite of the
# fault-tolerance PR; see docs/fault_tolerance.md), plus the resumable
# streams / mid-stream failover suite under its recovery seed sets —
# both run pre-merge.
chaos:
	@set -e; for seeds in $(CHAOS_SEED_SETS); do \
		echo "=== chaos suite, CHAOS_SEEDS=$$seeds ==="; \
		env CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_fault_tolerance.py -q -m chaos; \
	done; \
	for seeds in $(RECOVERY_SEED_SETS); do \
		echo "=== recovery suite, CHAOS_SEEDS=$$seeds ==="; \
		env CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_resumable.py -q -m chaos; \
	done; \
	for seeds in $(OVERLOAD_SEED_SETS); do \
		echo "=== overload suite, CHAOS_SEEDS=$$seeds ==="; \
		env CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_overload.py -q -m chaos; \
	done; \
	for seeds in $(SPEC_SEED_SETS); do \
		echo "=== spec-on identity suites (DYN_SPEC=ngram), CHAOS_SEEDS=$$seeds ==="; \
		env DYN_SPEC=ngram CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_resumable.py tests/test_overload.py -q -m "not slow"; \
	done; \
	for seeds in $(CHAOS_SEED_SETS); do \
		echo "=== KV conservation ledger suite, CHAOS_SEEDS=$$seeds ==="; \
		env CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_kv_ledger.py -q -m chaos; \
	done; \
	for seeds in $(TIERING_SEED_SETS); do \
		echo "=== predictive KV tiering sweep, CHAOS_SEEDS=$$seeds ==="; \
		env CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_kv_tiering.py -q -m chaos; \
	done; \
	for seeds in $(RECLAIM_SEED_SETS); do \
		echo "=== spot-reclamation suite, CHAOS_SEEDS=$$seeds ==="; \
		env CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_reclaim.py -q -m chaos; \
	done; \
	for seeds in $(SPEC_SEED_SETS); do \
		echo "=== spec-on reclaim identity (DYN_SPEC=ngram), CHAOS_SEEDS=$$seeds ==="; \
		env DYN_SPEC=ngram CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_reclaim.py -q -m chaos; \
	done; \
	for seeds in $(STORE_SEED_SETS); do \
		echo "=== durable-KV storage-fault suite, CHAOS_SEEDS=$$seeds ==="; \
		env CHAOS_SEEDS=$$seeds $(PYTEST) tests/test_kv_persist.py -q -m chaos; \
	done

# Seeded simulator regression sets (mirrors `make chaos`): every seed
# set re-runs the sim suite — determinism and calibration must hold for
# each (docs/simulation.md). The marked-slow fleet-scale runs are
# excluded here; `make sim-scale` runs them.
sim:
	@set -e; for seeds in $(SIM_SEED_SETS); do \
		echo "=== sim suite, SIM_SEEDS=$$seeds ==="; \
		env SIM_SEEDS=$$seeds $(PYTEST) tests/test_sim.py -q -m "sim and not slow"; \
	done

sim-scale:
	$(PYTEST) tests/test_sim.py -q -m "sim and slow"

# Flight-recorder demo (docs/observability.md): tiny engine, SIGUSR1,
# render the dump with `llmctl flight`.
flight:
	env JAX_PLATFORMS=cpu python examples/flight_demo.py

# Profiler-overhead smoke: the instrumented decode path must perform
# ZERO additional host syncs per window (sync-spy shim, not wall clock
# — CPU timing is load-sensitive).
profile-smoke:
	$(PYTEST) tests/test_dispatch_profile.py -q -k overhead

# AOT warm-boot smoke (docs/aot.md): boot an engine twice against a
# tmp persistent compile-cache dir; the second boot must compile
# NOTHING — zero ragged compile misses, zero variant growth under
# traffic, zero new cache entries. Runs pre-merge (pre-merge.yml).
prewarm-smoke:
	env JAX_PLATFORMS=cpu python -m dynamo_exp_tpu.llmctl aot smoke \
		--preset tiny --max-decode-slots 2 --page-size 8 \
		--max-model-len 128 --prefill-chunk 16 --decode-window 4 \
		--kv-dtype float32

# Style lint (ruff) + dynlint, the AST invariant checkers
# (docs/static_analysis.md): host-sync / determinism / thread-ownership
# / recompile-hazard over the package tree, zero unwaived findings.
# Runs in the pre-merge lane next to `make chaos`; the same gate is a
# tier-1 test (tests/test_analysis.py).
lint:
	ruff check dynamo_exp_tpu/ tests/ bench.py __graft_entry__.py
	python -m dynamo_exp_tpu.llmctl lint --json

# Bench regression comparator (docs/observability.md "Fleet plane"):
# compare the two newest checked-in BENCH_r*.json captures and fail on
# >10% tok/s or TTFT/ITL regressions per metric. Platform-tag aware:
# chip lines never compare against CPU-fallback lines, and captures
# with no comparable pairs (failed runs — the tunnel has been down
# since r02) compare clean. Runs pre-merge (pre-merge.yml).
bench-compare:
	@files=$$(ls BENCH_r*.json 2>/dev/null | sort | tail -2); \
	set -- $$files; \
	if [ $$# -lt 2 ]; then echo "fewer than two BENCH_r*.json files; nothing to compare"; exit 0; fi; \
	python -m dynamo_exp_tpu.llmctl bench compare $$1 $$2

# Request-anatomy + workload-fingerprint smoke (docs/observability.md
# "Request anatomy" / "Workload fingerprint"): decompose every trace in
# the checked-in fixture (`--why` waterfalls must render, components
# summing to the edge latency), list the worst-N, and fingerprint the
# fixture — the digest is deterministic, so it is pinned here and in
# tests/test_anatomy.py; a bucketing or hashing change must touch both.
# Runs pre-merge (pre-merge.yml).
anatomy-smoke:
	env JAX_PLATFORMS=cpu python -m dynamo_exp_tpu.llmctl trace aaaa1111 \
		--trace-file tests/fixtures/anatomy_trace.jsonl --why
	env JAX_PLATFORMS=cpu python -m dynamo_exp_tpu.llmctl trace bbbb2222 \
		--trace-file tests/fixtures/anatomy_trace.jsonl --why
	env JAX_PLATFORMS=cpu python -m dynamo_exp_tpu.llmctl slow \
		--trace-file tests/fixtures/anatomy_trace.jsonl -n 5
	env JAX_PLATFORMS=cpu python -m dynamo_exp_tpu.llmctl fingerprint \
		tests/fixtures/anatomy_trace.jsonl

# Autotuner smoke (docs/tuning.md): `llmctl tune` against the
# checked-in workload-fingerprint fixture — seeded search over the
# knob registry must beat the registry defaults in-sim (--check exits
# nonzero otherwise), and the journal/space digest must stay
# deterministic for the fixed seed. Runs pre-merge (pre-merge.yml).
tune-smoke:
	env JAX_PLATFORMS=cpu python -m dynamo_exp_tpu.llmctl tune \
		--fingerprint tests/fixtures/tune_fingerprint.json \
		--budget 96 --seed 0 --check --json
