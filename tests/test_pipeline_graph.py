"""Pipeline node-graph tests: frontends, operators, edge nodes, segment
cut points across a real transport.

Reference capability anchors:
``lib/runtime/src/pipeline/nodes.rs:1-351`` (Source/Sink/Operator/
ServiceFrontend/ServiceBackend/SegmentSource/SegmentSink),
``context.rs:1-467`` (Context id/registry/stages propagation).
"""

import asyncio

import pytest

from dynamo_exp_tpu.runtime import DistributedRuntime, LambdaEngine
from dynamo_exp_tpu.runtime.engine import AsyncEngineContext, ResponseStream
from dynamo_exp_tpu.runtime.pipeline import (
    Context,
    MapOperator,
    Operator,
    PipelineNode,
    PipelineOperator,
    SegmentSink,
    SegmentSource,
    ServiceBackend,
    ServiceFrontend,
    build_segment,
)


def counting_engine():
    """Engine yielding request['n'] integers 0..n-1."""

    async def gen(request, ctx):
        for i in range(request["n"]):
            yield {"i": i}

    return LambdaEngine(gen)


async def drain(stream):
    return [item async for item in stream]


# ------------------------------------------------------------- basic graph
async def test_frontend_backend_roundtrip():
    front = ServiceFrontend()
    front.link(ServiceBackend(counting_engine()))
    out = await drain(await front.generate({"n": 3}))
    assert out == [{"i": 0}, {"i": 1}, {"i": 2}]


async def test_edge_nodes_forward_and_backward():
    front = ServiceFrontend()
    front.link(
        PipelineNode(forward=lambda r: {"n": r["n"] + 1})
    ).link(
        PipelineNode(backward=lambda item: {"i": item["i"] * 10})
    ).link(ServiceBackend(counting_engine()))
    out = await drain(await front.generate({"n": 1}))
    assert out == [{"i": 0}, {"i": 10}]


async def test_pipeline_operator_sees_both_paths():
    """A bidirectional operator carries request info onto the response
    path — the capability edge nodes lack by design (nodes.rs doc)."""

    class Tagger(Operator):
        async def generate(self, request, next_engine, context):
            tag = request.pop("tag")
            stream = await next_engine.generate(request, context)

            async def wrapped():
                async for item in stream:
                    yield {**item, "tag": tag}

            return ResponseStream(wrapped(), context)

    front = build_segment([Tagger()], sink=counting_engine())
    out = await drain(await front.generate({"n": 2, "tag": "x"}))
    assert out == [{"i": 0, "tag": "x"}, {"i": 1, "tag": "x"}]


async def test_context_propagates_id_values_stages():
    seen = {}

    class Probe(Operator):
        async def generate(self, request, next_engine, context):
            seen["id"] = context.id
            return await next_engine.generate(request, context)

    front = build_segment([Probe()], sink=counting_engine())
    ctx = AsyncEngineContext("req-42")
    wrapped = Context({"n": 1}, controller=ctx)
    wrapped.insert("user", "alice")
    stream = await front.generate(wrapped)
    await drain(stream)
    assert seen["id"] == "req-42"
    assert wrapped.get("user") == "alice"
    assert wrapped.stages[0] == "ServiceFrontend"
    assert "Probe" in wrapped.stages


async def test_backend_error_propagates_to_caller():
    async def boom(request, ctx):
        raise RuntimeError("engine exploded")
        yield  # pragma: no cover

    class Boom:
        async def generate(self, request, context=None):
            raise RuntimeError("engine exploded")

    front = ServiceFrontend()
    front.link(ServiceBackend(Boom()))
    with pytest.raises(RuntimeError, match="engine exploded"):
        await front.generate({"n": 1})


async def test_unattached_segment_sink_fails_fast():
    front = ServiceFrontend()
    front.link(SegmentSink())
    with pytest.raises(RuntimeError, match="no transport"):
        await front.generate({"n": 1})


async def test_kill_stops_stream_mid_graph():
    front = ServiceFrontend()
    front.link(ServiceBackend(counting_engine()))
    ctx = AsyncEngineContext()
    stream = await front.generate({"n": 100}, ctx)
    got = []
    async for item in stream:
        got.append(item)
        if len(got) == 2:
            ctx.kill()
    assert len(got) == 2


# ------------------------------------------------- segment across transport
async def test_segment_cut_across_real_endpoint():
    """ingress segment → SegmentSink → (request plane) → SegmentSource →
    worker segment, over the in-process transport — the reference's
    frontend-node/worker-node split (SURVEY.md §3 ingress/worker call
    stacks)."""
    from dynamo_exp_tpu.runtime import Annotated, PushRouter, RouterMode

    drt = DistributedRuntime.detached()

    # Worker side: SegmentSource feeding a local graph ending in the
    # engine; served as a normal endpoint handler (which speaks
    # Annotated frames on the wire).
    async def annotated_counting(request, ctx):
        for i in range(request["n"]):
            yield Annotated.from_data({"i": i}).to_dict()

    worker_seg = SegmentSource()
    worker_seg.link(
        PipelineNode(forward=lambda r: {"n": r["n"] * 2})
    ).link(ServiceBackend(LambdaEngine(annotated_counting)))
    ep = drt.namespace("seg").component("worker").endpoint("generate")
    await ep.serve_endpoint(worker_seg.endpoint_handler())

    # Ingress side: frontend → backward-unwrap node → SegmentSink
    # attached to a PushRouter over the endpoint's live instances.
    client = await ep.client()
    sink = SegmentSink()
    front = ServiceFrontend()
    front.link(
        PipelineNode(backward=lambda fr: {"got": fr["i"]})
    ).link(sink)
    sink.attach(PushRouter(client, RouterMode.RANDOM))

    out = await drain(await front.generate({"n": 2}))
    assert out == [{"got": 0}, {"got": 1}, {"got": 2}, {"got": 3}]
    await drt.close()


# -------------------------------------------------------------- build sugar
async def test_build_segment_mixes_operators_and_nodes():
    front = build_segment(
        [
            MapOperator(map_request=lambda r: {"n": r["n"] + 1}),
            PipelineNode(backward=lambda item: item["i"]),
        ],
        sink=counting_engine(),
    )
    assert await drain(await front.generate({"n": 0})) == [0]


async def test_build_segment_rejects_double_link():
    front = ServiceFrontend()
    front.link(ServiceBackend(counting_engine()))
    with pytest.raises(RuntimeError, match="already linked"):
        front.link(ServiceBackend(counting_engine()))


async def test_forward_map_exception_fails_request_not_hangs():
    """A sync exception in a PipelineNode forward map under a
    PipelineOperator must error the caller's request — not leak the
    operator's slot and hang generate() forever."""

    class PassThrough(Operator):
        async def generate(self, request, next_engine, context):
            return await next_engine.generate(request, context)

    def bad_map(r):
        raise KeyError("malformed request")

    front = ServiceFrontend()
    op = PipelineOperator(PassThrough())
    front.link(op)
    op.link(PipelineNode(forward=bad_map)).link(
        ServiceBackend(counting_engine())
    )
    with pytest.raises(KeyError, match="malformed request"):
        await asyncio.wait_for(front.generate({"n": 1}), timeout=2)
