"""JSONL event recorder with rotation, plus replay.

Capability parity with ``/root/reference/lib/llm/src/recorder.rs:26-674``
(generic JSONL recorder with file rotation and a ``Recorder<T>`` replay)
and ``kv_router/recorder.rs`` (``KvRecorder`` taps the router-event
stream for offline analysis / index rebuilds).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import time
from typing import Any, AsyncIterator, Iterator

logger = logging.getLogger(__name__)


class Recorder:
    """Append-only JSONL event log; rotates at ``max_bytes`` keeping up to
    ``max_files`` older generations (``path``, ``path.1``, ``path.2``…)."""

    def __init__(self, path: str, max_bytes: int = 64 << 20, max_files: int = 4):
        self.path = path
        self.max_bytes = max_bytes
        self.max_files = max_files
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")
        self.count = 0

    def record(self, event: Any, ts: float | None = None) -> None:
        line = json.dumps({"ts": ts if ts is not None else time.time(), "event": event})
        self._fh.write(line + "\n")
        self._fh.flush()
        self.count += 1
        if self._fh.tell() >= self.max_bytes:
            self._rotate()

    def _rotate(self) -> None:
        self._fh.close()
        oldest = f"{self.path}.{self.max_files}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_files - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self._fh.close()

    @staticmethod
    def replay(path: str) -> Iterator[tuple[float, Any]]:
        """Yield (ts, event) from one JSONL file, oldest line first.
        Corrupt lines (e.g. a torn write at crash) are skipped."""
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                try:
                    d = json.loads(line)
                    yield float(d["ts"]), d["event"]
                except (ValueError, KeyError):
                    logger.warning("skipping corrupt recorder line")


class KvRecorder:
    """Taps a KV-router event subject into a Recorder, and replays a
    recording into an indexer — rebuild-from-log, the reference's
    ``KvRecorder`` capability (``kv_router/recorder.rs``)."""

    def __init__(self, recorder: Recorder):
        self.recorder = recorder
        self._task: asyncio.Task | None = None

    async def start(self, event_plane, subject: str) -> None:
        # Subscribe before returning: events published right after
        # start() must land in the recording.
        stream = await event_plane.subscribe(subject)

        async def pump(stream) -> None:
            # Re-subscribe on connection loss so a coordinator blip
            # doesn't silently end the recording. A dead generator is
            # never re-iterated: each drain failure discards the stream
            # and keeps retrying the subscribe itself until it succeeds.
            while True:
                try:
                    async for event in stream:
                        self.recorder.record(event)
                    return  # subscription closed cleanly
                except asyncio.CancelledError:
                    return
                except Exception as exc:
                    logger.warning("kv recorder stream lost (%s); retrying", exc)
                stream = None
                while stream is None:
                    await asyncio.sleep(1.0)
                    try:
                        stream = await event_plane.subscribe(subject)
                    except asyncio.CancelledError:
                        return
                    except Exception:
                        pass

        self._task = asyncio.ensure_future(pump(stream))

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        self.recorder.close()

    @staticmethod
    def replay_into(path: str, indexer) -> int:
        """Feed a recording's RouterEvents into a KvIndexer; returns the
        number of events applied."""
        from .kv_router.protocols import RouterEvent

        n = 0
        for _ts, event in Recorder.replay(path):
            indexer.apply(RouterEvent.from_dict(event))
            n += 1
        return n
